"""mx.contrib — AMP, quantization, ONNX, tensorboard
(python/mxnet/contrib analog)."""
from . import amp
from . import quantization
from . import tensorboard


def __getattr__(name):
    # onnx loads lazily: it needs google.protobuf, which must not become
    # a hard dependency of unrelated contrib users (amp/quantization)
    if name == "onnx":
        import importlib
        return importlib.import_module(".onnx", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
