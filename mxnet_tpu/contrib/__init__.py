"""mx.contrib — AMP, quantization, ONNX (python/mxnet/contrib analog)."""
from . import amp
from . import quantization
from . import onnx
