"""mx.contrib — AMP, quantization, ONNX-stub (python/mxnet/contrib analog)."""
from . import amp
from . import quantization
