"""ONNX export/import (python/mxnet/contrib/onnx analog).

Self-contained: the ONNX IR schema subset is compiled from
``onnx_minimal.proto`` (field layout matches the public onnx.proto, so
the files are real ONNX) — no onnx-package dependency. Scope: the op
set used by the model-zoo MLP/CNN families (Gemm/Conv/BatchNorm/
pooling/activations/elementwise/shape ops), opset 13.

- :func:`export_model` — Symbol + params → ``model.onnx``
- :func:`import_model` — ``model.onnx`` → (Symbol, arg_params, aux_params)

Round-trip is covered by tests through the compiled executor;
cross-validation against onnxruntime requires an environment that has
it installed.
"""
from __future__ import annotations

import numpy as np

from . import onnx_minimal_pb2 as pb
from ...base import MXNetError

__all__ = ["export_model", "import_model"]

_DT = {"float32": pb.TensorProto.FLOAT, "float64": pb.TensorProto.DOUBLE,
       "float16": pb.TensorProto.FLOAT16, "bfloat16": pb.TensorProto.BFLOAT16,
       "int32": pb.TensorProto.INT32, "int64": pb.TensorProto.INT64,
       "int8": pb.TensorProto.INT8, "uint8": pb.TensorProto.UINT8,
       "bool": pb.TensorProto.BOOL}
_DT_REV = {v: k for k, v in _DT.items()}

_UNARY_EXPORT = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
                 "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
                 "negative": "Neg", "identity": "Identity",
                 "copy": "Identity", "Flatten": "Flatten", "erf": "Erf",
                 "floor": "Floor", "ceil": "Ceil", "round": "Round"}
_BINARY_EXPORT = {"broadcast_add": "Add", "broadcast_sub": "Sub",
                  "broadcast_mul": "Mul", "broadcast_div": "Div",
                  "elemwise_add": "Add", "maximum": "Max", "minimum": "Min",
                  "broadcast_maximum": "Max", "broadcast_minimum": "Min"}


def _np_tensor(name, arr):
    t = pb.TensorProto()
    t.name = name
    t.dims.extend(arr.shape)
    dt = str(arr.dtype) if str(arr.dtype) in _DT else "float32"
    t.data_type = _DT[dt]
    a = np.ascontiguousarray(arr)
    if dt == "bfloat16":
        t.raw_data = a.view(np.uint16).tobytes()
    else:
        t.raw_data = a.astype(np.dtype(dt)).tobytes()
    return t


def _tensor_np(t):
    dtype = _DT_REV.get(t.data_type, "float32")
    shape = tuple(t.dims)
    if t.raw_data:
        if dtype == "bfloat16":
            import jax.numpy as jnp
            return np.frombuffer(t.raw_data, np.uint16).reshape(shape).view(jnp.bfloat16)
        return np.frombuffer(t.raw_data, np.dtype(dtype)).reshape(shape).copy()
    if t.float_data:
        return np.asarray(t.float_data, np.float32).reshape(shape)
    if t.int64_data:
        return np.asarray(t.int64_data, np.int64).reshape(shape)
    if t.int32_data:
        return np.asarray(t.int32_data, np.int32).reshape(shape)
    return np.zeros(shape, np.dtype(dtype))


def _attr(name, value):
    a = pb.AttributeProto()
    a.name = name
    if isinstance(value, float):
        a.type = pb.AttributeProto.FLOAT
        a.f = value
    elif isinstance(value, bool):
        a.type = pb.AttributeProto.INT
        a.i = int(value)
    elif isinstance(value, int):
        a.type = pb.AttributeProto.INT
        a.i = value
    elif isinstance(value, str):
        a.type = pb.AttributeProto.STRING
        a.s = value.encode()
    elif isinstance(value, (list, tuple)):
        if value and isinstance(value[0], float):
            a.type = pb.AttributeProto.FLOATS
            a.floats.extend(value)
        elif value and isinstance(value[0], str):
            a.type = pb.AttributeProto.STRINGS
            a.strings.extend(v.encode() for v in value)
        else:
            a.type = pb.AttributeProto.INTS
            a.ints.extend(int(v) for v in value)
    else:
        raise MXNetError(f"unsupported attribute {name}={value!r}")
    return a


class _Exporter:
    def __init__(self, graph):
        self.g = graph
        self.counter = 0
        self.extra_inits = []

    def uniq(self, base):
        self.counter += 1
        return f"{base}_{self.counter}"

    def node(self, op_type, inputs, outputs=None, name=None, **attrs):
        n = pb.NodeProto()
        n.op_type = op_type
        n.name = name or self.uniq(op_type.lower())
        n.input.extend(inputs)
        out = outputs or [n.name + "_out"]
        n.output.extend(out)
        for k, v in attrs.items():
            if v is not None:
                n.attribute.append(_attr(k, v))
        self.g.node.append(n)
        return out[0]

    def const_i64(self, vals):
        name = self.uniq("const")
        self.g.initializer.append(
            _np_tensor(name, np.asarray(vals, np.int64)))
        return name


def _tup(v, n=None):
    if v is None:
        return None
    t = tuple(int(x) for x in (v if isinstance(v, (list, tuple)) else (v,)))
    return t


# ---------------------------------------------------------------------------
# fused RNN op <-> ONNX LSTM/GRU/RNN (reference mx2onnx _op_translations
# RNN coverage). Gate-order maps between the cuDNN-canonical packed
# vector (op_impl_rnn.py: LSTM [i,f,g,o], GRU [r,z,n]) and the ONNX
# layouts (LSTM [i,o,f,c], GRU [z,r,h], W/R/B stacked per direction).
_RNN_GATES = {"lstm": 4, "gru": 3, "rnn_relu": 1, "rnn_tanh": 1}
_RNN_ONNX_OP = {"lstm": "LSTM", "gru": "GRU",
                "rnn_relu": "RNN", "rnn_tanh": "RNN"}
# ours->onnx block permutation (onnx = ours[perm])
_RNN_PERM = {"lstm": (0, 3, 1, 2), "gru": (1, 0, 2),
             "rnn_relu": (0,), "rnn_tanh": (0,)}
# onnx->ours (inverse permutation)
_RNN_INV = {"lstm": (0, 2, 3, 1), "gru": (1, 0, 2),
            "rnn_relu": (0,), "rnn_tanh": (0,)}


def _gate_perm(mat, mode, perm_table):
    """Permute the H-row gate blocks of a (gates*H, X) matrix or
    (gates*H,) bias vector."""
    gates = _RNN_GATES[mode]
    perm = list(perm_table[mode])
    blocks = mat.reshape((gates, mat.shape[0] // gates) + mat.shape[1:])
    return blocks[perm].reshape(mat.shape)


def _rnn_unpack(packed, mode, H, L, D):
    """Split the cuDNN-canonical flat vector into per-layer/direction
    (w_i2h, w_h2h, b_i2h, b_h2h) numpy arrays (layout per
    op_impl_rnn._unpack_params; input size inferred from total length)."""
    gates = _RNN_GATES[mode]
    rest_w = (L - 1) * D * gates * H * (D * H + H)
    total_b = L * D * gates * H * 2
    first_w = packed.size - rest_w - total_b
    isz0 = first_w // (D * gates * H) - H
    if isz0 <= 0 or D * gates * H * (isz0 + H) != first_w:
        raise MXNetError(
            f"packed RNN parameter vector of size {packed.size} does not "
            f"match mode={mode} H={H} L={L} D={D}")
    ws = []
    idx = 0
    for layer in range(L):
        isz = isz0 if layer == 0 else D * H
        per = []
        for _ in range(D):
            w_i2h = packed[idx:idx + gates * H * isz].reshape(gates * H, isz)
            idx += gates * H * isz
            w_h2h = packed[idx:idx + gates * H * H].reshape(gates * H, H)
            idx += gates * H * H
            per.append([w_i2h, w_h2h])
        ws.append(per)
    for layer in range(L):
        for d in range(D):
            b_i2h = packed[idx:idx + gates * H]
            idx += gates * H
            b_h2h = packed[idx:idx + gates * H]
            idx += gates * H
            ws[layer][d].extend([b_i2h, b_h2h])
    return ws


def _export_rnn(ex, a, ins, params_lookup):
    """Emit ONNX LSTM/GRU/RNN node(s) for one fused-RNN application;
    returns [output, h_out(, c_out)] names."""
    mode = str(a.get("mode", "lstm"))
    if mode not in _RNN_GATES:
        raise MXNetError(f"RNN mode {mode!r} not exportable")
    H = int(a["state_size"])
    L = int(a.get("num_layers", 1))
    D = 2 if str(a.get("bidirectional", False)) in ("True", "1", "true") \
        else 1
    packed = params_lookup(ins[1])
    if packed is None:
        raise MXNetError(
            "RNN export needs the packed parameter vector as a constant "
            f"initializer; {ins[1]!r} is a free graph input")
    layers = _rnn_unpack(np.asarray(packed, np.float32).ravel(),
                         mode, H, L, D)
    lstm = mode == "lstm"
    onnx_op = _RNN_ONNX_OP[mode]

    def state_for(layer, name):
        if L == 1:
            return name  # already (D, N, H)
        return ex.node("Slice",
                       [name, ex.const_i64([layer * D]),
                        ex.const_i64([(layer + 1) * D]), ex.const_i64([0])])

    x = ins[0]
    hs, cs = [], []
    for layer in range(L):
        W = np.stack([_gate_perm(d[0], mode, _RNN_PERM)
                      for d in layers[layer]])
        R = np.stack([_gate_perm(d[1], mode, _RNN_PERM)
                      for d in layers[layer]])
        B = np.stack([np.concatenate([_gate_perm(d[2], mode, _RNN_PERM),
                                      _gate_perm(d[3], mode, _RNN_PERM)])
                      for d in layers[layer]])
        wn, rn, bn = (ex.uniq(f"rnn_{t}{layer}") for t in ("W", "R", "B"))
        for nm, arr in ((wn, W), (rn, R), (bn, B)):
            ex.g.initializer.append(_np_tensor(nm, arr))
        node_ins = [x, wn, rn, bn, "", state_for(layer, ins[2])]
        if lstm:
            node_ins.append(state_for(layer, ins[3]))
        outs = [ex.uniq("rnn_Y"), ex.uniq("rnn_Yh")]
        if lstm:
            outs.append(ex.uniq("rnn_Yc"))
        kw = {"hidden_size": H,
              "direction": "bidirectional" if D == 2 else "forward"}
        if mode == "gru":
            # cuDNN computes n = tanh(Wx + r*(Rh + bR))
            kw["linear_before_reset"] = 1
        if onnx_op == "RNN":
            kw["activations"] = ["Relu" if mode == "rnn_relu"
                                 else "Tanh"] * D
        ex.node(onnx_op, node_ins, outs, **kw)
        # Y (T, D, N, H) -> (T, N, D*H) for the next layer / output
        tr = ex.node("Transpose", [outs[0]], perm=(0, 2, 1, 3))
        x = ex.node("Reshape", [tr, ex.const_i64((0, 0, D * H))])
        hs.append(outs[1])
        if lstm:
            cs.append(outs[2])
    h = hs[0] if L == 1 else ex.node("Concat", hs, axis=0)
    if lstm:
        c = cs[0] if L == 1 else ex.node("Concat", cs, axis=0)
        return [x, h, c]
    return [x, h]


def _export_node(ex, op_name, attrs, ins, out_name=None,
                 params_lookup=None):
    """Map one mxnet op application to ONNX node(s); returns the output
    name (or a LIST of names for multi-output ops like RNN)."""
    a = {k: v for k, v in attrs.items() if v is not None}
    if op_name == "RNN":
        return _export_rnn(ex, a, ins, params_lookup)
    if op_name in _UNARY_EXPORT:
        return ex.node(_UNARY_EXPORT[op_name], ins, [out_name] if out_name else None)
    if op_name in _BINARY_EXPORT:
        return ex.node(_BINARY_EXPORT[op_name], ins, [out_name] if out_name else None)
    if op_name == "FullyConnected":
        x = ins[0]
        if str(a.get("flatten", True)) not in ("False", "0"):
            x = ex.node("Flatten", [x], axis=1)
        inputs = [x, ins[1]] + (ins[2:3] if len(ins) > 2 else [])
        return ex.node("Gemm", inputs, [out_name] if out_name else None,
                       alpha=1.0, beta=1.0, transB=1)
    if op_name == "Convolution":
        k = _tup(a.get("kernel"))
        nd_ = len(k)
        pads = _tup(a.get("pad")) or (0,) * nd_
        return ex.node("Conv", ins, [out_name] if out_name else None,
                       kernel_shape=k,
                       strides=_tup(a.get("stride")) or (1,) * nd_,
                       pads=pads + pads,
                       dilations=_tup(a.get("dilate")) or (1,) * nd_,
                       group=int(a.get("num_group", 1)))
    if op_name == "Activation":
        t = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
             "softrelu": "Softplus"}[a.get("act_type", "relu")]
        return ex.node(t, ins, [out_name] if out_name else None)
    if op_name == "LeakyReLU":
        act = a.get("act_type", "leaky")
        if act == "leaky":
            return ex.node("LeakyRelu", ins, [out_name] if out_name else None,
                           alpha=float(a.get("slope", 0.25)))
        if act == "elu":
            return ex.node("Elu", ins, [out_name] if out_name else None,
                           alpha=float(a.get("slope", 0.25)))
        raise MXNetError(f"LeakyReLU act_type {act} not exportable")
    if op_name in ("softmax", "SoftmaxActivation", "SoftmaxOutput", "Softmax"):
        return ex.node("Softmax", ins[:1], [out_name] if out_name else None,
                       axis=int(a.get("axis", -1)))
    if op_name == "log_softmax":
        return ex.node("LogSoftmax", ins, [out_name] if out_name else None,
                       axis=int(a.get("axis", -1)))
    if op_name == "Pooling":
        global_pool = str(a.get("global_pool", False)) in ("True", "1")
        ptype = a.get("pool_type", "max")
        if global_pool:
            t = "GlobalMaxPool" if ptype == "max" else "GlobalAveragePool"
            return ex.node(t, ins, [out_name] if out_name else None)
        k = _tup(a.get("kernel"))
        pads = _tup(a.get("pad")) or (0,) * len(k)
        kw = dict(kernel_shape=k,
                  strides=_tup(a.get("stride")) or (1,) * len(k),
                  pads=pads + pads)
        # pooling_convention="full" is mxnet's ceil_mode (gluon
        # MaxPool2D(ceil_mode=True)); dropping it silently shifted
        # squeezenet's pool shapes by one
        if str(a.get("pooling_convention", "valid")) == "full":
            kw["ceil_mode"] = 1
        if ptype == "max":
            return ex.node("MaxPool", ins, [out_name] if out_name else None, **kw)
        kw["count_include_pad"] = int(str(a.get("count_include_pad", True))
                                      in ("True", "1"))
        return ex.node("AveragePool", ins, [out_name] if out_name else None, **kw)
    if op_name == "BatchNorm":
        return ex.node("BatchNormalization", ins[:5],
                       [out_name] if out_name else None,
                       epsilon=float(a.get("eps", 1e-5)),
                       momentum=float(a.get("momentum", 0.9)))
    if op_name == "Dropout":
        return ex.node("Dropout", ins[:1], [out_name] if out_name else None)
    if op_name in ("concat", "Concat"):
        return ex.node("Concat", ins, [out_name] if out_name else None,
                       axis=int(a.get("dim", 1)))
    if op_name == "add_n":
        return ex.node("Sum", ins, [out_name] if out_name else None)
    if op_name in ("reshape", "Reshape"):
        shape = ex.const_i64(_tup(a.get("shape")))
        return ex.node("Reshape", [ins[0], shape],
                       [out_name] if out_name else None)
    if op_name == "transpose":
        return ex.node("Transpose", ins, [out_name] if out_name else None,
                       perm=_tup(a.get("axes")))
    if op_name == "dot":
        return ex.node("MatMul", ins, [out_name] if out_name else None)
    if op_name == "Embedding":
        # onnx Gather(data=table, indices)
        return ex.node("Gather", [ins[1], ins[0]],
                       [out_name] if out_name else None, axis=0)
    if op_name == "clip":
        # bounds arrive as attrs (a_min/a_max kwargs) or as scalar
        # positional inputs (sym.clip(x, 0, 6))
        lo = a.get("a_min", ins[1] if len(ins) > 1 else 0.0)
        hi = a.get("a_max", ins[2] if len(ins) > 2 else 0.0)
        ex_lo = ex.uniq("clip_min")
        ex_hi = ex.uniq("clip_max")
        ex.g.initializer.append(_np_tensor(
            ex_lo, np.asarray(float(lo), np.float32)))
        ex.g.initializer.append(_np_tensor(
            ex_hi, np.asarray(float(hi), np.float32)))
        return ex.node("Clip", [ins[0], ex_lo, ex_hi],
                       [out_name] if out_name else None)
    raise MXNetError(f"op {op_name!r} has no ONNX export mapping")


def export_model(sym, params, input_shapes=None, input_types=None,
                 onnx_file_path="model.onnx", opset_version=13, **kwargs):
    """Export a Symbol + params dict to an ONNX file.

    params: dict name→NDArray covering every non-data argument.
    input_shapes: dict name→shape (or list matching free inputs)."""
    from ...symbol.symbol import Symbol

    if not isinstance(sym, Symbol):
        raise MXNetError("export_model expects a Symbol")
    params = {k.split(":", 1)[-1]: v for k, v in (params or {}).items()}
    model = pb.ModelProto()
    model.ir_version = 8
    model.producer_name = "mxnet_tpu"
    opset = model.opset_import.add()
    opset.domain = ""
    opset.version = opset_version
    g = model.graph
    g.name = sym.name or "mxnet_tpu_graph"
    ex = _Exporter(g)

    for name, arr in params.items():
        g.initializer.append(_np_tensor(name, arr.asnumpy()))

    shapes = dict(input_shapes or {})
    names: dict = {}

    def params_lookup(name):
        arr = params.get(name)
        return arr.asnumpy() if arr is not None else None

    def first(v):
        # a multi-output node used directly as an input means output 0
        return v[0] if isinstance(v, list) else v

    def emit(node):
        if node._base is not None:
            outs = emit(node._base)
            if isinstance(outs, list):
                return outs[node._output_index or 0]
            return outs  # single-output subset
        if id(node) in names:
            return names[id(node)]
        if node._op is None:
            names[id(node)] = node._name
            if node._name not in params:
                vi = g.input.add()
                vi.name = node._name
                vi.type.tensor_type.elem_type = pb.TensorProto.FLOAT
                for d in shapes.get(node._name, ()):
                    vi.type.tensor_type.shape.dim.add().dim_value = int(d)
            return node._name
        # scalar positional args (sym.clip(x, 0, 6)) ride through as
        # python values for the op branch to fold into attributes
        ins = [first(emit(i)) if isinstance(i, Symbol) else i
               for i in node._inputs]
        attrs = {k: v for k, v in node._attrs.items() if not k.startswith("__")}
        out = _export_node(ex, node._op.name, attrs, ins,
                           out_name=node._name + "_out" if node._name else None,
                           params_lookup=params_lookup)
        names[id(node)] = out
        return out

    outputs = sym._inputs if sym._is_group() else [sym]
    for o in outputs:
        out_name = first(emit(o))
        vi = g.output.add()
        vi.name = out_name
        vi.type.tensor_type.elem_type = pb.TensorProto.FLOAT

    with open(onnx_file_path, "wb") as f:
        f.write(model.SerializeToString())
    return onnx_file_path


# ---------------------------------------------------------------------------
# import
# ---------------------------------------------------------------------------
_UNARY_IMPORT = {v: k for k, v in _UNARY_EXPORT.items() if v != "Identity"}
_UNARY_IMPORT["Identity"] = "identity"
_BINARY_IMPORT = {"Add": "broadcast_add", "Sub": "broadcast_sub",
                  "Mul": "broadcast_mul", "Div": "broadcast_div",
                  "Max": "broadcast_maximum", "Min": "broadcast_minimum"}


def _sym_pads(pads, nd_, op_type):
    """ONNX pads = [begin..., end...]; the mxnet ops take symmetric pads.
    Asymmetric padding raises loudly instead of silently truncating."""
    if not pads:
        return (0,) * nd_
    pads = tuple(pads)
    begin, end = pads[:nd_], pads[nd_:]
    if end and begin != end:
        raise MXNetError(
            f"{op_type}: asymmetric ONNX pads {pads} are not supported")
    return begin


def _get_attrs(n):
    out = {}
    for a in n.attribute:
        if a.type == pb.AttributeProto.FLOAT:
            out[a.name] = a.f
        elif a.type == pb.AttributeProto.INT:
            out[a.name] = a.i
        elif a.type == pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == pb.AttributeProto.INTS:
            out[a.name] = tuple(a.ints)
        elif a.type == pb.AttributeProto.FLOATS:
            out[a.name] = tuple(a.floats)
        elif a.type == pb.AttributeProto.STRINGS:
            out[a.name] = tuple(s.decode() for s in a.strings)
    return out


def _import_rnn(symmod, nd, n, a, ins, inits, env, arg_params, sym_of):
    """ONNX LSTM/GRU/RNN node → fused sym.RNN; returns
    {onnx_output_name: Symbol} for the outputs the node declares."""
    t = n.op_type
    H = int(a["hidden_size"])
    direction = a.get("direction", "forward")
    if direction == "reverse":
        raise MXNetError(f"{t}: direction='reverse' is not supported "
                         "(wrap in bidirectional or flip the sequence)")
    D = 2 if direction == "bidirectional" else 1
    if t == "LSTM":
        mode = "lstm"
    elif t == "GRU":
        mode = "gru"
        if not int(a.get("linear_before_reset", 0)):
            raise MXNetError(
                "GRU with linear_before_reset=0 differs from the "
                "cuDNN-canonical cell this framework computes")
    else:
        acts = tuple(s.lower() for s in a.get("activations", ("tanh",) * D))
        if any(s != acts[0] for s in acts) or acts[0] not in ("tanh", "relu"):
            raise MXNetError(f"RNN activations {acts} not supported")
        mode = f"rnn_{acts[0]}"
    gates = _RNN_GATES[mode]
    W = inits.get(ins[1])
    R = inits.get(ins[2])
    if W is None or R is None:
        raise MXNetError(f"{t}: W/R must be constant initializers")
    B = inits.get(ins[3]) if len(ins) > 3 and ins[3] else \
        np.zeros((D, 2 * gates * H), np.float32)
    if B is None:
        raise MXNetError(f"{t}: B must be a constant initializer")
    ws, bs = [], []
    for d in range(D):
        ws.append(_gate_perm(np.asarray(W[d], np.float32),
                             mode, _RNN_INV).ravel())
        ws.append(_gate_perm(np.asarray(R[d], np.float32),
                             mode, _RNN_INV).ravel())
        bs.append(_gate_perm(np.asarray(B[d][:gates * H], np.float32),
                             mode, _RNN_INV))
        bs.append(_gate_perm(np.asarray(B[d][gates * H:], np.float32),
                             mode, _RNN_INV))
    packed = np.concatenate(ws + bs)
    pname = (n.name or f"{t.lower()}_{n.output[0]}") + "_parameters"
    env[pname] = ("var", symmod.var(pname))
    arg_params[pname] = nd.array(packed)
    # W/R/B are consumed into the packed vector — they must not linger
    # as free parameters the caller would have to feed
    for consumed in ins[1:4]:
        arg_params.pop(consumed, None)
    if len(ins) > 4 and ins[4]:
        raise MXNetError(
            f"{t}: sequence_lens input is not supported — the fused RNN "
            "would run the full recurrence over padding and silently "
            "diverge from the ONNX-spec masked result")
    if len(ins) > 5 and ins[5]:
        init_h = sym_of(ins[5])
    else:
        raise MXNetError(
            f"{t}: initial_h input is required (implicit zero states "
            "need a static batch size this importer does not carry)")
    args = [sym_of(ins[0]), env[pname][1], init_h]
    if mode == "lstm":
        if len(ins) > 6 and ins[6]:
            args.append(sym_of(ins[6]))
        else:
            raise MXNetError("LSTM: initial_c input is required")
    r = symmod.RNN(*args, state_size=H, num_layers=1,
                   bidirectional=D == 2, mode=mode, state_outputs=True)
    # our output (T, N, D*H) -> ONNX Y (T, D, N, H)
    y = symmod.transpose(symmod.reshape(r[0], shape=(0, 0, D, H)),
                         axes=(0, 2, 1, 3))
    out = {n.output[0]: y} if n.output[0] else {}
    if len(n.output) > 1 and n.output[1]:
        out[n.output[1]] = r[1]
    if mode == "lstm" and len(n.output) > 2 and n.output[2]:
        out[n.output[2]] = r[2]
    if not out:
        out = {"_unused": y}
    return out


def import_model(onnx_file_path):
    """Load an ONNX file → (Symbol, arg_params, aux_params)."""
    from ... import symbol as symmod
    from ... import ndarray as nd

    model = pb.ModelProto()
    with open(onnx_file_path, "rb") as f:
        model.ParseFromString(f.read())
    g = model.graph
    inits = {t.name: _tensor_np(t) for t in g.initializer}
    env: dict = {}
    arg_params = {}
    for name, arr in inits.items():
        if (arr.dtype == np.int64 and arr.ndim <= 1) or arr.ndim == 0:
            # shape/axes constants and scalar attrs-as-inputs (Clip
            # min/max): plain python-side values, never parameters
            env[name] = ("const", arr)
        else:
            env[name] = ("var", symmod.var(name))
            arg_params[name] = nd.array(arr)
    for vi in g.input:
        if vi.name not in env:
            env[vi.name] = ("var", symmod.var(vi.name))

    def val(name):
        kind, v = env[name]
        if kind == "const":
            return v
        return v

    def sym_of(name):
        kind, v = env[name]
        if kind == "const":
            raise MXNetError(f"{name} is a constant, not a tensor input")
        return v

    for n in g.node:
        a = _get_attrs(n)
        t = n.op_type
        ins = list(n.input)
        multi = None  # multi-output nodes set {output_name: sym}
        if t in ("LSTM", "GRU", "RNN"):
            multi = _import_rnn(symmod, nd, n, a, ins, inits, env,
                                arg_params, sym_of)
            res = next(iter(multi.values()))
        elif t == "Slice":
            starts = np.asarray(val(ins[1])).ravel()
            ends = np.asarray(val(ins[2])).ravel()
            axes = (np.asarray(val(ins[3])).ravel() if len(ins) > 3
                    else np.arange(starts.size))
            if len(ins) > 4:
                steps = np.asarray(val(ins[4])).ravel()
                if (steps != 1).any():
                    raise MXNetError("Slice with steps != 1 not supported")
            res = sym_of(ins[0])
            for ax, b, e in zip(axes, starts, ends):
                res = symmod.slice_axis(res, axis=int(ax), begin=int(b),
                                        end=int(e))
        elif t in _UNARY_IMPORT:
            res = getattr(symmod, "flatten" if t == "Flatten" else _UNARY_IMPORT[t])(sym_of(ins[0])) \
                if t != "Flatten" else symmod.Flatten(sym_of(ins[0]))
        elif t in _BINARY_IMPORT:
            res = getattr(symmod, _BINARY_IMPORT[t])(sym_of(ins[0]), sym_of(ins[1]))
        elif t == "Gemm":
            alpha = float(a.get("alpha", 1.0))
            beta = float(a.get("beta", 1.0))
            trans_a = bool(a.get("transA", 0))
            trans_b = bool(a.get("transB", 0))
            bias = sym_of(ins[2]) if len(ins) > 2 else None
            w_arr = arg_params.get(ins[1])
            if (trans_b and not trans_a and alpha == 1.0
                    and (bias is None or beta == 1.0)):
                # the common (and our exporter's) convention → FC op
                num_hidden = int(w_arr.shape[0]) if w_arr is not None else 0
                res = symmod.FullyConnected(
                    sym_of(ins[0]), sym_of(ins[1]), bias,
                    num_hidden=num_hidden, no_bias=bias is None,
                    flatten=False)
            else:
                # general Gemm: alpha*op(A)·op(B) + beta*C
                A = sym_of(ins[0])
                B = sym_of(ins[1])
                res = symmod.dot(A, B, transpose_a=trans_a,
                                 transpose_b=trans_b)
                if alpha != 1.0:
                    res = res * alpha
                if bias is not None:
                    res = symmod.broadcast_add(
                        res, bias * beta if beta != 1.0 else bias)
        elif t == "MatMul":
            res = symmod.dot(sym_of(ins[0]), sym_of(ins[1]))
        elif t == "Conv":
            k = tuple(a["kernel_shape"])
            nd_ = len(k)
            pads = _sym_pads(a.get("pads"), nd_, t)
            bias = sym_of(ins[2]) if len(ins) > 2 else None
            w_arr = arg_params.get(ins[1])
            res = symmod.Convolution(
                sym_of(ins[0]), sym_of(ins[1]), bias, kernel=k,
                stride=tuple(a.get("strides", (1,) * nd_)), pad=pads,
                dilate=tuple(a.get("dilations", (1,) * nd_)),
                num_filter=int(w_arr.shape[0]) if w_arr is not None else 0,
                num_group=int(a.get("group", 1)), no_bias=bias is None)
        elif t in ("MaxPool", "AveragePool"):
            k = tuple(a["kernel_shape"])
            pads = _sym_pads(a.get("pads"), len(k), t)
            res = symmod.Pooling(
                sym_of(ins[0]), kernel=k,
                pool_type="max" if t == "MaxPool" else "avg",
                stride=tuple(a.get("strides", (1,) * len(k))), pad=pads,
                pooling_convention=("full" if int(a.get("ceil_mode", 0))
                                    else "valid"),
                # ONNX spec default: EXCLUDE padding from the average
                count_include_pad=bool(a.get("count_include_pad", 0)))
        elif t in ("GlobalMaxPool", "GlobalAveragePool"):
            res = symmod.Pooling(sym_of(ins[0]), global_pool=True,
                                 pool_type="max" if t == "GlobalMaxPool" else "avg")
        elif t == "BatchNormalization":
            res = symmod.BatchNorm(*[sym_of(i) for i in ins[:5]],
                                   eps=float(a.get("epsilon", 1e-5)),
                                   momentum=float(a.get("momentum", 0.9)),
                                   fix_gamma=False, use_global_stats=True)
        elif t == "Softmax":
            res = symmod.softmax(sym_of(ins[0]), axis=int(a.get("axis", -1)))
        elif t == "LogSoftmax":
            res = symmod.log_softmax(sym_of(ins[0]), axis=int(a.get("axis", -1)))
        elif t == "Dropout":
            res = symmod.Dropout(sym_of(ins[0]))
        elif t == "Concat":
            res = symmod.concat(*[sym_of(i) for i in ins],
                                dim=int(a.get("axis", 1)))
        elif t == "Sum":
            res = symmod.add_n(*[sym_of(i) for i in ins])
        elif t == "Reshape":
            shape = tuple(int(x) for x in val(ins[1]))
            res = symmod.reshape(sym_of(ins[0]), shape=shape)
        elif t == "Transpose":
            res = symmod.transpose(sym_of(ins[0]), axes=tuple(a.get("perm", ())))
        elif t == "Gather":
            res = symmod.Embedding(sym_of(ins[1]), sym_of(ins[0]),
                                   input_dim=0, output_dim=0)
        elif t == "Clip":
            lo = float(val(ins[1])) if len(ins) > 1 else a.get("min", 0.0)
            hi = float(val(ins[2])) if len(ins) > 2 else a.get("max", 0.0)
            res = symmod.clip(sym_of(ins[0]), a_min=lo, a_max=hi)
        elif t in ("LeakyRelu", "Elu"):
            res = symmod.LeakyReLU(
                sym_of(ins[0]),
                act_type="leaky" if t == "LeakyRelu" else "elu",
                slope=float(a.get("alpha", 0.25)))
        elif t == "Softplus":
            res = symmod.Activation(sym_of(ins[0]), act_type="softrelu")
        else:
            raise MXNetError(f"ONNX op {t!r} has no import mapping")
        if multi is not None:
            for out_name, s in multi.items():
                env[out_name] = ("var", s)
        else:
            env[n.output[0]] = ("var", res)

    outputs = [sym_of(vi.name) for vi in g.output]
    out_sym = outputs[0] if len(outputs) == 1 else symmod.Group(outputs)
    # split aux (BN running stats) from args by conventional names
    aux_params = {k: v for k, v in arg_params.items()
                  if k.endswith(("moving_mean", "moving_var",
                                 "running_mean", "running_var"))}
    for k in aux_params:
        arg_params.pop(k)
    return out_sym, arg_params, aux_params
