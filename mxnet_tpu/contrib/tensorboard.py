"""TensorBoard scalar logging (reference python/mxnet/contrib/tensorboard.py
+ SURVEY §5.5 'optional TensorBoard scalar writer built-in').

Upstream wraps the external tensorboard package's SummaryWriter; this
backend is SELF-CONTAINED: it writes the TensorBoard event-file format
directly (TFRecord framing with masked CRC32C + the tiny Event/Summary
protobuf subset scalars need), so `tensorboard --logdir` reads the
output with zero extra dependencies in the image.
"""
from __future__ import annotations

import os
import socket
import struct
import time

__all__ = ["SummaryWriter", "LogMetricsCallback"]

# -- crc32c (Castagnoli), table-driven — TFRecord framing needs it -----
_CRC_TABLE = []


def _crc_table():
    if not _CRC_TABLE:
        poly = 0x82F63B78
        for n in range(256):
            c = n
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            _CRC_TABLE.append(c)
    return _CRC_TABLE


def _crc32c(data: bytes) -> int:
    tab = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tab[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- minimal protobuf writers ------------------------------------------
def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire: int) -> bytes:
    return _varint(field << 3 | wire)


def _pb_double(field: int, v: float) -> bytes:
    return _tag(field, 1) + struct.pack("<d", v)


def _pb_float(field: int, v: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", v)


def _pb_int(field: int, v: int) -> bytes:
    return _tag(field, 0) + _varint(v)


def _pb_bytes(field: int, v: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(v)) + v


def _event(wall_time: float, step: int | None = None,
           file_version: str | None = None, summary: bytes | None = None):
    out = _pb_double(1, wall_time)
    if step is not None:
        out += _pb_int(2, step)
    if file_version is not None:
        out += _pb_bytes(3, file_version.encode())
    if summary is not None:
        out += _pb_bytes(5, summary)
    return out


class SummaryWriter:
    """Write scalar summaries TensorBoard can read.

    >>> sw = SummaryWriter("/tmp/logs/run1")
    >>> sw.add_scalar("loss", 0.5, step)
    """

    _SEQ = [0]  # per-process uniquifier

    def __init__(self, logdir, filename_suffix=""):
        os.makedirs(logdir, exist_ok=True)
        # pid + sequence uniquify concurrent writers in one logdir (two
        # writers in the same second would otherwise truncate each
        # other — real tensorboard embeds pid for the same reason)
        SummaryWriter._SEQ[0] += 1
        fname = (f"events.out.tfevents.{int(time.time())}."
                 f"{socket.gethostname()}.{os.getpid()}."
                 f"{SummaryWriter._SEQ[0]}{filename_suffix}")
        self._path = os.path.join(logdir, fname)
        self._f = open(self._path, "wb")
        self._write_record(_event(time.time(), file_version="brain.Event:2"))

    def _write_record(self, data: bytes):
        hdr = struct.pack("<Q", len(data))
        self._f.write(hdr)
        self._f.write(struct.pack("<I", _masked_crc(hdr)))
        self._f.write(data)
        self._f.write(struct.pack("<I", _masked_crc(data)))

    def add_scalar(self, tag, value, global_step=0, walltime=None):
        value_pb = _pb_bytes(1, str(tag).encode()) + _pb_float(2, float(value))
        summary = _pb_bytes(1, value_pb)
        self._write_record(_event(walltime if walltime is not None
                                  else time.time(),
                                  step=int(global_step), summary=summary))

    def flush(self):
        self._f.flush()

    def close(self):
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    @property
    def path(self):
        return self._path

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class LogMetricsCallback:
    """Batch-end callback streaming eval metrics to TensorBoard
    (reference contrib/tensorboard.py LogMetricsCallback — same
    constructor contract, no external dependency)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.summary_writer = SummaryWriter(logging_dir)
        self._step = 0

    def __call__(self, param):
        if param.eval_metric is None:
            return
        self._step += 1
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = f"{self.prefix}-{name}"
            self.summary_writer.add_scalar(name, value, self._step)
        # live tensorboard must see scalars as they land; a crashed run
        # must not lose its history to the file buffer
        self.summary_writer.flush()
