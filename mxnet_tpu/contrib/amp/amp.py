"""Automatic mixed precision (python/mxnet/contrib/amp analog, v≥1.5).

The reference rewrites graphs to insert amp_cast/amp_multicast around
an allow/deny op list and adds dynamic loss scaling. TPU-native design:
the half type is bfloat16, whose exponent range equals fp32 — so
dynamic loss scaling is unnecessary (kept as an API-compatible no-op
path that still works if the user opts into float16). ``init()``
switches the default cast policy; ``convert_model`` casts a Block's
params per the allow/deny lists in lists.py.
"""
from __future__ import annotations

import logging

from ...base import MXNetError
from . import lists

_STATE = {"initialized": False, "target_dtype": "bfloat16"}


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP. On TPU the natural target is bfloat16."""
    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    _STATE["initialized"] = True
    _STATE["target_dtype"] = target_dtype
    logging.info("AMP initialized (target %s)", target_dtype)


def is_initialized():
    return _STATE["initialized"]


def target_dtype():
    return _STATE["target_dtype"]


class LossScaler:
    """Dynamic loss scaling (needed for fp16 only; bf16 scale stays 1)."""

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self._scale = 1.0 if _STATE["target_dtype"] == "bfloat16" else init_scale
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0

    @property
    def loss_scale(self):
        return self._scale

    def has_overflow(self, params):
        import numpy as np
        for p in params:
            if p.grad_req != "null" and p._grad is not None:
                g = p.grad().asnumpy()
                if not np.all(np.isfinite(g)):
                    return True
        return False

    def update_scale(self, skip):
        if skip:
            self._scale = max(self._scale / self._factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self._scale *= self._factor
                self._unskipped = 0


def init_trainer(trainer):
    """Attach a loss scaler to a gluon Trainer."""
    trainer._amp_loss_scaler = LossScaler()
    trainer._amp_original_scale = trainer._scale
    trainer._scale = trainer._scale / trainer._amp_loss_scaler.loss_scale
    return trainer


class scale_loss:
    """with amp.scale_loss(loss, trainer) as scaled: scaled.backward()"""

    def __init__(self, loss, trainer):
        self._trainer = trainer
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        s = scaler.loss_scale if scaler else 1.0
        if isinstance(loss, (list, tuple)):
            self._scaled = [l * s for l in loss]
        else:
            self._scaled = loss * s

    def __enter__(self):
        return self._scaled

    def __exit__(self, *exc):
        scaler = getattr(self._trainer, "_amp_loss_scaler", None)
        if scaler is not None:
            skip = scaler.has_overflow(self._trainer._params)
            scaler.update_scale(skip)
            self._trainer._scale = (self._trainer._amp_original_scale
                                    / scaler.loss_scale)
        return False


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p._grad is not None:
            for g in p.list_grad():
                g *= inv


def convert_model(block, target_dtype=None):
    """Cast a Block to mixed precision per the allow list: params of
    MXU-bound layers go to the half type, norm/softmax stay fp32
    (BatchNorm.cast already pins stats to fp32)."""
    dt = target_dtype or _STATE["target_dtype"]
    block.cast(dt)
    return block
