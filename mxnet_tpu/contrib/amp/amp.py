"""Automatic mixed precision (python/mxnet/contrib/amp analog, v≥1.5).

The reference rewrites the GRAPH, inserting amp_cast/amp_multicast
around an allow/deny op list. TPU-native form of the same rewrite:
``init()`` installs a dispatch-level cast hook
(ndarray/register.set_dispatch_cast_hook) that casts every op's tensor
inputs per the lists — MXU-bound ops (matmul/conv/RNN) to the half
type, numerics-sensitive ops to fp32, widest-type ops to a common
float. Because hybridize/CachedOp traces and the compiled symbolic
executor both run through the hooked dispatch, compiled graphs carry
the casts exactly like the reference's rewritten symbols. The half type
is bfloat16 — exponent range equals fp32, so dynamic loss scaling stays
at 1.0 (the fp16 scaler is kept API-compatible).
"""
from __future__ import annotations

import logging

import jax.numpy as jnp

from ...base import MXNetError
from . import lists

_STATE = {"initialized": False, "target_dtype": "bfloat16",
          "target_ops": None, "fp32_ops": None, "widest_ops": None}

_HALF = {"bfloat16": jnp.bfloat16, "float16": jnp.float16}
_FLOATS = (jnp.float32, jnp.bfloat16, jnp.float16, jnp.float64)


def _is_float(a):
    return getattr(a, "dtype", None) in _FLOATS


def _cast_hook(op, arrays):
    """The amp_cast/amp_multicast insertion, applied at dispatch."""
    name = op.name
    if name in _STATE["target_ops"]:
        half = _HALF[_STATE["target_dtype"]]
        return [a.astype(half) if _is_float(a) and a.dtype != half else a
                for a in arrays]
    if name in _STATE["fp32_ops"]:
        return [a.astype(jnp.float32)
                if _is_float(a) and a.dtype != jnp.float32 else a
                for a in arrays]
    if name in _STATE["widest_ops"]:
        dts = [a.dtype for a in arrays if _is_float(a)]
        if len(set(dts)) > 1:
            widest = jnp.float32 if jnp.float32 in dts else max(
                dts, key=lambda d: jnp.finfo(d).bits)
            return [a.astype(widest) if _is_float(a) else a for a in arrays]
    return arrays


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable AMP: install the dispatch cast hook (reference
    amp.init graph-patching analog). Extra op lists extend the
    defaults in lists.py."""
    from ...ndarray.register import set_dispatch_cast_hook

    if target_dtype not in ("bfloat16", "float16"):
        raise MXNetError("target_dtype must be bfloat16 or float16")
    _STATE["initialized"] = True
    _STATE["target_dtype"] = target_dtype
    _STATE["target_ops"] = set(lists.TARGET_DTYPE_OPS) | set(target_precision_ops or ())
    _STATE["fp32_ops"] = set(lists.FP32_OPS) | set(fp32_ops or ()) \
        | set(conditional_fp32_ops or ())
    _STATE["widest_ops"] = set(lists.WIDEST_TYPE_CASTS)
    set_dispatch_cast_hook(_cast_hook)
    logging.info("AMP initialized (target %s)", target_dtype)


def disable():
    """Remove the cast hook (mainly for tests)."""
    from ...ndarray.register import set_dispatch_cast_hook

    _STATE["initialized"] = False
    set_dispatch_cast_hook(None)


def is_initialized():
    return _STATE["initialized"]


def target_dtype():
    return _STATE["target_dtype"]


class LossScaler:
    """Dynamic loss scaling. Only active for float16 — bf16's exponent
    range equals fp32, so the scale pins to 1 and the per-step overflow
    scan (a host sync over every gradient) is skipped entirely."""

    MAX_SCALE = 2.0 ** 24

    def __init__(self, init_scale=2.0 ** 16, scale_factor=2.0,
                 scale_window=2000):
        self.active = _STATE["target_dtype"] == "float16"
        self._scale = init_scale if self.active else 1.0
        self._factor = scale_factor
        self._window = scale_window
        self._unskipped = 0

    @property
    def loss_scale(self):
        return self._scale

    def has_overflow(self, params):
        import numpy as np
        if not self.active:
            return False
        for p in params:
            if p.grad_req != "null" and p._grad is not None:
                g = p.grad().asnumpy()
                if not np.all(np.isfinite(g)):
                    return True
        return False

    def update_scale(self, skip):
        if skip:
            self._scale = max(self._scale / self._factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped >= self._window:
                self._scale = min(self._scale * self._factor, self.MAX_SCALE)
                self._unskipped = 0


def init_trainer(trainer):
    """Attach a loss scaler to a gluon Trainer and wrap step() so an
    overflowed iteration SKIPS the weight update (reference AMP
    contract) instead of applying inf/NaN gradients."""
    scaler = LossScaler()
    trainer._amp_loss_scaler = scaler
    trainer._amp_original_scale = trainer._scale
    trainer._scale = trainer._scale / scaler.loss_scale
    orig_step = trainer.step

    def amp_step(batch_size, ignore_stale_grad=False):
        skip = scaler.has_overflow(trainer._params)
        # unscale with the scale that was IN EFFECT during backward;
        # only then adjust it for the next iteration
        trainer._scale = trainer._amp_original_scale / scaler.loss_scale
        scaler.update_scale(skip)
        if skip:
            logging.warning("AMP: gradient overflow, skipping update "
                            "(loss scale -> %g)", scaler.loss_scale)
            return
        return orig_step(batch_size, ignore_stale_grad)

    trainer.step = amp_step
    return trainer


class scale_loss:
    """with amp.scale_loss(loss, trainer) as scaled: scaled.backward()"""

    def __init__(self, loss, trainer):
        from ... import autograd

        self._trainer = trainer
        scaler = getattr(trainer, "_amp_loss_scaler", None)
        s = scaler.loss_scale if scaler else 1.0
        if s == 1.0:
            # bf16 default: no scaling needed — pass the taped loss
            # through untouched (a multiply here would sit OUTSIDE the
            # record scope and detach the graph)
            self._scaled = loss
        else:
            # fp16: the scaling multiply must be ON the tape even though
            # scale_loss is conventionally entered after record() closes
            with autograd.record(train_mode=autograd.is_training()):
                if isinstance(loss, (list, tuple)):
                    self._scaled = [l * s for l in loss]
                else:
                    self._scaled = loss * s

    def __enter__(self):
        return self._scaled

    def __exit__(self, *exc):
        # overflow handling moved into the wrapped trainer.step (which
        # must SKIP the update); nothing to do at scope exit
        return False


def unscale(trainer):
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for p in trainer._params:
        if p.grad_req != "null" and p._grad is not None:
            for g in p.list_grad():
                g *= inv


def convert_model(block, target_dtype=None):
    """Cast a Block to mixed precision per the allow list: params of
    MXU-bound layers go to the half type, norm/softmax stay fp32
    (BatchNorm.cast already pins stats to fp32)."""
    dt = target_dtype or _STATE["target_dtype"]
    block.cast(dt)
    return block
