from .amp import (init, disable, is_initialized, target_dtype, init_trainer,
                  scale_loss, unscale, convert_model, LossScaler)
from . import lists
