"""AMP op lists (contrib/amp/lists/symbol.py analog): which ops run in
the half type vs fp32. On TPU, MXU ops (matmul/conv/RNN) are the
bf16 winners; reductions and normalizations accumulate in fp32."""

# run in the target half type (MXU-bound)
TARGET_DTYPE_OPS = [
    "FullyConnected", "Convolution", "Deconvolution", "dot", "batch_dot",
    "matmul", "RNN", "Embedding", "linalg_gemm", "linalg_gemm2",
]

# always fp32 (numerics-sensitive)
FP32_OPS = [
    "softmax", "log_softmax", "SoftmaxOutput", "softmax_cross_entropy",
    "BatchNorm", "LayerNorm", "InstanceNorm", "GroupNorm", "L2Normalization",
    "mean", "sum", "norm", "exp", "log",
]

# fp32 unless inputs already half (conditional)
CONDITIONAL_FP32_OPS = []

# run in wider of input dtypes
WIDEST_TYPE_CASTS = ["broadcast_add", "broadcast_sub", "broadcast_mul",
                     "broadcast_div", "add_n", "concat", "where"]
