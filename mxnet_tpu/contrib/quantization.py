"""INT8 quantization (python/mxnet/contrib/quantization.py analog).

The reference's INT8 path (src/operator/quantization/*): quantize/
dequantize ops, calibration (minmax/entropy) collecting layer ranges,
and a graph rewrite to quantized kernels. TPU-native design:

- fused int8 compute ops (ndarray/op_impl_quant.py) whose matmul/conv
  run s8×s8→s32 on the MXU (``preferred_element_type=int32``);
- :func:`quantize_net` REWRITES a Gluon net in place, swapping every
  ``nn.Dense`` / ``nn.Conv2D`` child for a :class:`QuantizedDense` /
  :class:`QuantizedConv2D` holding int8 weights; activations use the
  calibrated per-layer input range when calibration data is given
  (static quantization) and the per-batch max otherwise (dynamic);
- :func:`quantize_model` keeps the legacy symbol-API signature; the
  symbol graph is annotated (the compute rewrite is the Gluon path —
  reference parity note: the legacy path there also rides a subgraph
  backend that this design replaces with block rewriting).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["CalibrationCollector", "calib_graph", "quantize_model",
           "quantize_net", "QuantizedDense", "QuantizedConv2D"]


class CalibrationCollector:
    """Collects per-layer min/max over calibration batches
    (reference _LayerOutputMinMaxCollector)."""

    def __init__(self):
        self.min_max = {}

    def collect(self, name, arr):
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        lo, hi = float(a.min()), float(a.max())
        if name in self.min_max:
            plo, phi = self.min_max[name]
            lo, hi = min(lo, plo), max(hi, phi)
        self.min_max[name] = (lo, hi)


def calib_graph(net, calib_data, num_batches=10, inputs=False):
    """Run calibration batches through a Block, hooking layer outputs
    (or inputs with ``inputs=True`` — what the int8 layers consume)."""
    collector = CalibrationCollector()
    handles = []

    def walk(block):
        for name, child in block._children.items():
            if inputs:
                def make_pre(n):
                    def hook(blk, ins):
                        collector.collect(n, ins[0])
                    return hook
                handles.append(child.register_forward_pre_hook(make_pre(child.name)))
            else:
                def make_hook(n):
                    def hook(blk, ins, output):
                        collector.collect(n, output)
                    return hook
                handles.append(child.register_forward_hook(make_hook(child.name)))
            walk(child)

    walk(net)
    seen = 0
    for batch in calib_data:
        data = batch[0] if isinstance(batch, (list, tuple)) else batch.data[0]
        net(data)
        seen += 1
        if seen >= num_batches:
            break
    for h in handles:
        h.detach()
    return collector.min_max


from ..gluon.block import HybridBlock  # noqa: E402
from ..gluon import nn as _nn  # noqa: E402


class _QuantizedBase(HybridBlock):
    """Holds int8 weight + scale quantized ONCE from a float layer.

    All state lives in registered Parameters (weight_q/weight_scale/
    act_amax/bias) so quantized nets checkpoint through the normal
    save_parameters/load_parameters path; act_amax <= 0 means dynamic
    per-batch activation ranges (resolved in-graph, no sync)."""

    def _quantize_weight(self, float_layer, ctx, act_range, fold_bn=None,
                         channelwise=False):
        from .. import ndarray as nd
        from ..ndarray.op_impl_quant import quantize_weight
        from ..ndarray.ndarray import _wrap
        w = float_layer.weight.data(ctx)
        wf = w._data.astype("float32")
        fold_bias = None
        if fold_bn is not None:
            # fold the BN inference affine into the conv (reference
            # mkldnn int8 fuses conv+BN the same way): w' = w*g/sigma
            # per out-channel, b' = beta - mu*g/sigma (+ b*g/sigma)
            import jax.numpy as jnp
            gam = fold_bn.gamma.data(ctx)._data.astype("float32")
            bet = fold_bn.beta.data(ctx)._data.astype("float32")
            mu = fold_bn.running_mean.data(ctx)._data.astype("float32")
            var = fold_bn.running_var.data(ctx)._data.astype("float32")
            bscale = gam / jnp.sqrt(var + fold_bn._epsilon)
            wf = wf * bscale.reshape((-1,) + (1,) * (wf.ndim - 1))
            b0 = (float_layer.bias.data(ctx)._data.astype("float32")
                  if float_layer.bias is not None else 0.0)
            fold_bias = bet - mu * bscale + b0 * bscale
        q, s = quantize_weight(wf, channelwise=channelwise)
        with self.name_scope():
            self.weight_q = self.params.get(
                "weight_q", shape=q.shape, dtype="int8", init="zeros",
                grad_req="null")
            self.weight_scale = self.params.get(
                "weight_scale", shape=s.shape, dtype="float32", init="zeros",
                grad_req="null")
            self.act_amax = self.params.get(
                "act_amax", shape=(1,), dtype="float32", init="zeros",
                grad_req="null")
            self.bias = None
            if fold_bias is not None:
                self.bias = self.params.get(
                    "bias", shape=fold_bias.shape, dtype="float32",
                    init="zeros", grad_req="null")
            elif float_layer.bias is not None:
                self.bias = self.params.get(
                    "bias", shape=float_layer.bias.shape, dtype="float32",
                    init="zeros", grad_req="null")
        self.collect_params().initialize(ctx=ctx)
        self.weight_q.set_data(_wrap(q, ctx))
        self.weight_scale.set_data(_wrap(s, ctx))
        amax = (max(abs(act_range[0]), abs(act_range[1]))
                if act_range is not None else -1.0)  # <=0 → dynamic
        self.act_amax.set_data(nd.array([amax], ctx=ctx))
        if fold_bias is not None:
            from ..ndarray.ndarray import _wrap as _w2
            self.bias.set_data(_w2(fold_bias, ctx))
        elif self.bias is not None:
            self.bias.set_data(float_layer.bias.data(ctx))


class QuantizedDense(_QuantizedBase):
    """int8 replacement for nn.Dense (reference
    quantized_fully_connected): weights pre-quantized, activations
    quantized per call (static range when calibrated)."""

    def __init__(self, float_layer, act_range=None, ctx=None, prefix=None):
        super().__init__(prefix=prefix or (float_layer.name + "_int8_"))
        from ..context import current_context
        ctx = ctx or current_context()
        self._units = float_layer._units
        self._flatten = float_layer._flatten
        self._act = float_layer.act
        self._quantize_weight(float_layer, ctx, act_range)

    def forward(self, x):
        from ..ndarray.register import get_op, invoke
        from ..ndarray.op_impl_quant import quantize_act
        from ..ndarray.ndarray import _wrap
        q, s = quantize_act(x._data, self.act_amax.data(x.ctx)._data)
        bias = self.bias.data(x.ctx) if self.bias is not None else None
        out = invoke(get_op("quantized_fully_connected"),
                     [_wrap(q, x.ctx), self.weight_q.data(x.ctx),
                      _wrap(s, x.ctx), self.weight_scale.data(x.ctx), bias],
                     {"num_hidden": self._units, "flatten": self._flatten,
                      "no_bias": bias is None})
        out = out.astype(x.dtype)
        return self._act(out) if self._act is not None else out


class QuantizedConv2D(_QuantizedBase):
    """int8 replacement for nn.Conv2D (reference quantized_conv)."""

    def __init__(self, float_layer, act_range=None, ctx=None, prefix=None,
                 fold_bn=None):
        super().__init__(prefix=prefix or (float_layer.name + "_int8_"))
        from ..context import current_context
        ctx = ctx or current_context()
        self._kwargs = dict(float_layer._kwargs)
        self._act = float_layer.act
        # s8-interface chaining (quantize_net(s8_interfaces=True) second
        # pass): _out_req holds the NEXT chained conv's act_amax
        # Parameter — the requantize epilogue target; _prequantized
        # means the input arrives already s8 at our own act_amax scale
        self._out_req = None
        self._prequantized = False
        # the float interface dtype of the original model (the layer
        # input dtype is s8 when prequantized — can't cast output to it)
        self._float_dtype = str(float_layer.weight.dtype)
        self._quantize_weight(float_layer, ctx, act_range, fold_bn=fold_bn,
                              channelwise=True)

    def forward(self, x):
        import jax.numpy as jnp
        from ..ndarray.register import get_op, invoke
        from ..ndarray.op_impl_quant import quantize_act, _amax_scale
        from ..ndarray.ndarray import _wrap
        if self._prequantized and str(x.dtype) == "int8":
            # producer already requantized into OUR calibrated scale
            q = x._data
            s = _amax_scale(self.act_amax.data(x.ctx)._data.reshape(())
                            ).reshape(1)
        else:
            q, s = quantize_act(x._data, self.act_amax.data(x.ctx)._data)
        bias = self.bias.data(x.ctx) if self.bias is not None else None
        kw = {k: v for k, v in self._kwargs.items()
              if k in ("kernel", "stride", "dilate", "pad", "num_filter",
                       "num_group")}
        inputs = [_wrap(q, x.ctx), self.weight_q.data(x.ctx),
                  _wrap(s, x.ctx), self.weight_scale.data(x.ctx), bias]
        no_bias = bias is None
        if self._out_req is not None:
            if bias is None:
                # placeholder: invoke only drops TRAILING None inputs
                inputs[4] = _wrap(jnp.zeros((1,), jnp.float32), x.ctx)
            inputs.append(self._out_req.data(x.ctx))
        out = invoke(get_op("quantized_conv"), inputs,
                     {**kw, "no_bias": no_bias})
        if self._out_req is not None:
            # s8 out rides to the chained consumer (relu/Identity
            # between us operate on s8 unchanged). An inline act here
            # would run on raw s8 CODES (wrong for anything nonlinear
            # beyond relu) — the chain pass only links act-free convs.
            assert self._act is None, \
                "s8-interface chain must not carry an inline activation"
            return out
        # keep bf16 interfaces bf16; a prequantized input is s8, so the
        # model's float dtype is the cast target then
        tgt = self._float_dtype if str(x.dtype) == "int8" else x.dtype
        out = out.astype(tgt)
        return self._act(out) if self._act is not None else out


def quantize_net(net, quantized_dtype="int8", calib_data=None,
                 calib_mode="naive", num_calib_examples=32, ctx=None,
                 exclude_layers=(), s8_interfaces=False, **kwargs):
    """Rewrite ``net`` so Dense/Conv2D children execute in int8.

    With ``calib_data``: per-layer INPUT ranges are collected first
    (static activation scales). Without: dynamic per-batch ranges.
    Returns the same net object (rewritten in place), reference-API
    compatible.

    Conv->BatchNorm pairs inside (Hybrid)Sequential containers are
    folded into the int8 conv (BN dropped); conv weight scales are
    per-out-channel. NOTE: int8 checkpoints written before the
    per-channel change (weight_scale shape (1,)) do not load into
    newly quantized nets."""
    if quantized_dtype != "int8":
        raise MXNetError(f"only int8 is supported, got {quantized_dtype}")
    if s8_interfaces and calib_data is None:
        # validate BEFORE the destructive in-place rewrite — raising
        # after it would leave the caller's net half-quantized
        raise MXNetError(
            "s8_interfaces=True needs calibrated (static) activation "
            "ranges — pass calib_data")
    # hybridized nets would run calibration hooks (which read concrete
    # values) inside a trace, and the cached compiled graph would keep
    # executing the FLOAT layers after the rewrite — deactivate and
    # drop caches first (re-hybridize after quantizing if desired)
    from ..gluon.block import Block as _Block
    if isinstance(net, _Block):
        net.hybridize(active=False)  # recurses; plain Blocks forward it
    ranges = {}
    if calib_data is not None:
        ranges = calib_graph(net, calib_data,
                             num_batches=max(1, num_calib_examples // 32),
                             inputs=True)

    def rewrite(block):
        items = list(block._children.items())
        for idx, (name, child) in enumerate(items):
            rewrite(child)
            if child.name in exclude_layers:
                continue
            if type(child) is _nn.Dense:
                qlayer = QuantizedDense(child, ranges.get(child.name), ctx)
            elif type(child) is _nn.Conv2D:
                # conv immediately followed by BatchNorm in the same
                # container: fold the BN inference affine into the int8
                # conv's weight/bias and drop the BN from the graph
                # (the chain around every conv — dequant->BN->quant —
                # was the measured reason int8 LOST to bf16)
                # fold only where adjacency IS dataflow (Sequential
                # containers), the conv has no inline activation (the
                # float graph is BN(act(conv)) then — folding would
                # reorder to act(BN(conv))), and the BN normalizes the
                # conv out-channel axis
                fold_bn = None
                if isinstance(block, (_nn.Sequential, _nn.HybridSequential)) \
                        and child.act is None \
                        and idx + 1 < len(items) \
                        and type(items[idx + 1][1]) is _nn.BatchNorm \
                        and items[idx + 1][1]._axis == 1 \
                        and items[idx + 1][1].name not in exclude_layers:
                    fold_bn = items[idx + 1][1]
                qlayer = QuantizedConv2D(child, ranges.get(child.name), ctx,
                                         fold_bn=fold_bn)
                if fold_bn is not None:
                    ident = _nn.Identity(prefix=fold_bn.name + "_folded_")
                    block._children[items[idx + 1][0]] = ident
                    for attr, val in list(vars(block).items()):
                        if val is fold_bn:
                            object.__setattr__(block, attr, ident)
            else:
                continue
            block._children[name] = qlayer
            # attribute access (net.fc1) must resolve to the new layer
            for attr, val in list(vars(block).items()):
                if val is child:
                    object.__setattr__(block, attr, qlayer)

    rewrite(net)

    if s8_interfaces:
        _chain_s8_interfaces(net)
    net._quantized_dtype = quantized_dtype
    net._quant_ranges = ranges
    return net


def _chain_s8_interfaces(net):
    """Second rewrite pass: within each (Hybrid)Sequential, when a
    QuantizedConv2D reaches the NEXT QuantizedConv2D through only
    Identity / relu-Activation children, requantize the producer's
    output straight into the consumer's calibrated input scale — the
    tensor between them stays s8 end-to-end (half the bf16 HBM bytes;
    the relu between them is exact on s8: requant-then-relu ==
    relu-then-requant for a symmetric scale). Residual-add boundaries
    (non-Sequential dataflow) stay bf16 — correctness first."""

    def passthrough(child):
        if type(child) is _nn.Identity:
            return True
        return (type(child) is _nn.Activation
                and getattr(child, "_act_type", None) == "relu")

    def walk(block):
        if isinstance(block, (_nn.Sequential, _nn.HybridSequential)):
            items = [c for _, c in block._children.items()]
            for i, child in enumerate(items):
                if not isinstance(child, QuantizedConv2D):
                    continue
                if child._act is not None:
                    continue  # inline act would run pre-requant
                j = i + 1
                while j < len(items) and passthrough(items[j]):
                    j += 1
                if j < len(items) and isinstance(items[j], QuantizedConv2D):
                    consumer = items[j]
                    amax = float(consumer.act_amax.data().asnumpy()[0])
                    if amax > 0:  # static calibrated range only
                        child._out_req = consumer.act_amax
                        consumer._prequantized = True
        for _, c in block._children.items():
            walk(c)

    walk(net)


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, calib_mode="naive", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8", **kwargs):
    """Legacy-API entry: returns (sym, arg_params, aux_params) with
    quantization annotations; the executing int8 path is the Gluon
    :func:`quantize_net` rewrite."""
    qsym = sym
    for node in qsym._topo():
        if node._op is not None and node._op.name in ("FullyConnected",
                                                      "Convolution", "dot"):
            node._attrs["__quantized_dtype__"] = quantized_dtype
    return qsym, arg_params, aux_params
