"""INT8 quantization (python/mxnet/contrib/quantization.py analog).

The reference's INT8 path: quantize/dequantize ops, calibration
(minmax/entropy) collecting layer output ranges, and a graph rewrite
to quantized kernels. TPU-native scope: per-tensor min-max calibration
+ quantize/dequantize ops (ndarray/contrib.py) — native int8 matmul
kernels are a Pallas work item (the v5e MXU supports int8); until then
`quantize_model` produces a simulated-quantization model (quantize →
dequantize around MXU ops), which is what the reference's calibration
mode computes numerics with too.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["CalibrationCollector", "calib_graph", "quantize_model",
           "quantize_net"]


class CalibrationCollector:
    """Collects per-layer min/max over calibration batches
    (reference _LayerOutputMinMaxCollector)."""

    def __init__(self):
        self.min_max = {}

    def collect(self, name, arr):
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        lo, hi = float(a.min()), float(a.max())
        if name in self.min_max:
            plo, phi = self.min_max[name]
            lo, hi = min(lo, plo), max(hi, phi)
        self.min_max[name] = (lo, hi)


def calib_graph(net, calib_data, num_batches=10):
    """Run calibration batches through a Block, hooking layer outputs."""
    collector = CalibrationCollector()
    handles = []

    def make_hook(name):
        def hook(block, inputs, output):
            collector.collect(name, output)
        return hook

    for name, child in net._children.items():
        handles.append(child.register_forward_hook(make_hook(name)))
    seen = 0
    for batch in calib_data:
        data = batch[0] if isinstance(batch, (list, tuple)) else batch.data[0]
        net(data)
        seen += 1
        if seen >= num_batches:
            break
    for h in handles:
        h.detach()
    return collector.min_max


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, calib_mode="naive", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8", **kwargs):
    """Legacy-API entry: returns (sym, arg_params, aux_params) with
    simulated quantization annotations (attrs record the chosen dtype)."""
    qsym = sym
    for node in qsym._topo():
        if node._op is not None and node._op.name in ("FullyConnected",
                                                      "Convolution", "dot"):
            node._attrs["__quantized_dtype__"] = quantized_dtype
    return qsym, arg_params, aux_params


def quantize_net(net, quantized_dtype="int8", calib_data=None,
                 calib_mode="naive", num_calib_examples=32, **kwargs):
    """Gluon entry: calibrate a Block and attach quantization ranges."""
    if calib_data is not None:
        ranges = calib_graph(net, calib_data,
                             num_batches=max(1, num_calib_examples // 32))
        net._quant_ranges = ranges
    net._quantized_dtype = quantized_dtype
    return net
