"""INT8 quantization (python/mxnet/contrib/quantization.py analog).

The reference's INT8 path (src/operator/quantization/*): quantize/
dequantize ops, calibration (minmax/entropy) collecting layer ranges,
and a graph rewrite to quantized kernels. TPU-native design:

- fused int8 compute ops (ndarray/op_impl_quant.py) whose matmul/conv
  run s8×s8→s32 on the MXU (``preferred_element_type=int32``);
- :func:`quantize_net` REWRITES a Gluon net in place, swapping every
  ``nn.Dense`` / ``nn.Conv2D`` child for a :class:`QuantizedDense` /
  :class:`QuantizedConv2D` holding int8 weights; activations use the
  calibrated per-layer input range when calibration data is given
  (static quantization) and the per-batch max otherwise (dynamic);
- :func:`quantize_model` keeps the legacy symbol-API signature; the
  symbol graph is annotated (the compute rewrite is the Gluon path —
  reference parity note: the legacy path there also rides a subgraph
  backend that this design replaces with block rewriting).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError

__all__ = ["CalibrationCollector", "calib_graph", "quantize_model",
           "quantize_net", "QuantizedDense", "QuantizedConv2D"]


class CalibrationCollector:
    """Collects per-layer activation statistics over calibration batches
    (reference _LayerOutputMinMaxCollector / _LayerHistogramCollector).

    ``mode="naive"``: running min/max. ``mode="entropy"``: additionally
    keeps the observed values so :meth:`ranges` can run the KL-optimal
    threshold search (reference _get_optimal_thresholds /
    src/operator/quantization/calibrate.cc) — the symmetric range that
    minimizes the KL divergence between the clipped distribution and
    its 255-level quantization, which ignores rare outliers that would
    otherwise stretch the int8 grid."""

    def __init__(self, mode="naive", num_bins=8001):
        if mode not in ("naive", "entropy"):
            raise MXNetError(f"unknown calib_mode {mode!r} "
                             "(expected 'naive' or 'entropy')")
        self.mode = mode
        self.num_bins = num_bins
        self.min_max = {}
        self._hists = {}   # name -> _RangeHistogram (entropy mode)

    def collect(self, name, arr):
        a = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        lo, hi = float(a.min()), float(a.max())
        if name in self.min_max:
            plo, phi = self.min_max[name]
            lo, hi = min(lo, plo), max(hi, phi)
        self.min_max[name] = (lo, hi)
        if self.mode == "entropy":
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = _RangeHistogram(self.num_bins)
            h.add(np.asarray(a, np.float32).ravel())

    def ranges(self):
        """Per-layer (lo, hi) to quantize against."""
        if self.mode == "naive":
            return self.min_max
        out = {}
        for name, h in self._hists.items():
            th = _optimal_threshold_hist(h.hist, h.edges())
            out[name] = (-th, th)
        return out


class _RangeHistogram:
    """Fixed-bin symmetric histogram whose range grows with the data:
    memory per layer is one (num_bins,) float array regardless of how
    many calibration batches run (the reference's histogram collector
    does the same; storing raw activations was O(total activations))."""

    def __init__(self, num_bins=8001):
        self.num_bins = num_bins
        self.amax = 0.0
        self.hist = np.zeros(num_bins, np.float64)

    def edges(self):
        return np.linspace(-self.amax, self.amax, self.num_bins + 1)

    def add(self, values):
        amax = float(np.abs(values).max()) if values.size else 0.0
        if amax > self.amax:
            if self.hist.any():
                # re-bin the existing mass into the wider range by its
                # old bin centers (bounded coarsening, standard practice)
                centers = 0.5 * (self.edges()[:-1] + self.edges()[1:])
                old = self.hist
                self.amax = amax
                self.hist = np.histogram(
                    centers, bins=self.num_bins,
                    range=(-amax, amax), weights=old)[0].astype(np.float64)
            else:
                self.amax = amax
        if self.amax == 0.0:
            return
        self.hist += np.histogram(values, bins=self.num_bins,
                                  range=(-self.amax, self.amax))[0]


def _smooth(p, eps=1e-4):
    """Move eps mass onto empty bins so KL(p||q) stays finite; returns
    None when the distribution has no support at all."""
    zeros = p == 0
    n_nonzero = p.size - int(zeros.sum())
    if n_nonzero == 0:
        return None
    off = eps * float(zeros.sum()) / n_nonzero
    out = p.astype(np.float64).copy()
    out[zeros] = eps
    out[~zeros] -= off
    if (out[~zeros] <= 0).any():
        return None
    return out


def _kl(p, q):
    p = p / p.sum()
    q = q / q.sum()
    mask = p > 0
    return float((p[mask] * np.log(p[mask] / q[mask])).sum())


def _optimal_threshold(values, num_bins=8001, num_quantized_bins=255):
    """KL-optimal threshold from raw values (tests / one-shot use;
    the collector path feeds :func:`_optimal_threshold_hist` from its
    memory-bounded histogram)."""
    amax = float(np.abs(values).max()) if values.size else 0.0
    if amax == 0.0:
        return 0.0
    hist, edges = np.histogram(values, bins=num_bins, range=(-amax, amax))
    return _optimal_threshold_hist(hist.astype(np.float64), edges,
                                   num_quantized_bins)


def _optimal_threshold_hist(hist, edges, num_quantized_bins=255):
    """KL-divergence-optimal symmetric clipping threshold (the TensorRT
    calibration recipe the reference implements in calibrate.cc): over
    a symmetric histogram, for each candidate half-width ``i`` bins,
    compare the clipped distribution P (outliers folded into the edge
    bins) against Q = P re-quantized to 255 levels; return the
    threshold with minimal KL(P||Q)."""
    num_bins = hist.shape[0]
    amax = float(edges[-1])
    if amax == 0.0 or not hist.any():
        return 0.0
    zero = num_bins // 2
    half_q = num_quantized_bins // 2
    best_th, best_kl = amax, np.inf
    for i in range(half_q + 1, zero + 1):
        lo, hi = zero - i, zero + i + 1
        p = hist[lo:hi].copy()
        p[0] += hist[:lo].sum()
        p[-1] += hist[hi:].sum()
        support = hist[lo:hi] != 0  # clipped-view support, pre-fold
        # quantize the sliced histogram into 255 equal-width groups:
        # each group's mass spreads uniformly over its occupied bins
        n = hi - lo
        merged = n // num_quantized_bins
        main = hist[lo:lo + merged * num_quantized_bins].reshape(
            num_quantized_bins, merged)
        gmass = main.sum(axis=1)
        gmass[-1] += hist[lo + merged * num_quantized_bins:hi].sum()
        occ = (main != 0).sum(axis=1).astype(np.float64)
        tail_occ = (hist[lo + merged * num_quantized_bins:hi] != 0).sum()
        occ[-1] += tail_occ
        per_bin = np.divide(gmass, occ, out=np.zeros_like(gmass),
                            where=occ > 0)
        q = np.repeat(per_bin, merged)
        q = np.concatenate([q, np.full(n - q.size, per_bin[-1])])
        q[~support] = 0.0
        ps, qs = _smooth(p), _smooth(q)
        if ps is None or qs is None:
            continue
        kl = _kl(ps, qs)
        if kl < best_kl:
            best_kl, best_th = kl, float(edges[hi])
    return best_th


def calib_graph(net, calib_data, num_batches=10, inputs=False,
                mode="naive"):
    """Run calibration batches through a Block, hooking layer outputs
    (or inputs with ``inputs=True`` — what the int8 layers consume)."""
    collector = CalibrationCollector(mode=mode)
    handles = []

    def walk(block):
        for name, child in block._children.items():
            # entropy mode accumulates an 8001-bin histogram per hooked
            # block — hook LEAVES only (the rewrite consumes leaf-layer
            # ranges; container hooks would histogram every tensor once
            # per nesting level for nothing)
            hook_this = mode != "entropy" or not child._children
            if not hook_this:
                pass
            elif inputs:
                def make_pre(n):
                    def hook(blk, ins):
                        collector.collect(n, ins[0])
                    return hook
                handles.append(child.register_forward_pre_hook(make_pre(child.name)))
            else:
                def make_hook(n):
                    def hook(blk, ins, output):
                        collector.collect(n, output)
                    return hook
                handles.append(child.register_forward_hook(make_hook(child.name)))
            walk(child)

    walk(net)
    seen = 0
    for batch in calib_data:
        data = batch[0] if isinstance(batch, (list, tuple)) else batch.data[0]
        net(data)
        seen += 1
        if seen >= num_batches:
            break
    for h in handles:
        h.detach()
    return collector.ranges()


from ..gluon.block import HybridBlock  # noqa: E402
from ..gluon import nn as _nn  # noqa: E402


class _QuantizedBase(HybridBlock):
    """Holds int8 weight + scale quantized ONCE from a float layer.

    All state lives in registered Parameters (weight_q/weight_scale/
    act_amax/bias) so quantized nets checkpoint through the normal
    save_parameters/load_parameters path; act_amax <= 0 means dynamic
    per-batch activation ranges (resolved in-graph, no sync)."""

    def _quantize_weight(self, float_layer, ctx, act_range, fold_bn=None,
                         channelwise=False):
        from .. import ndarray as nd
        from ..ndarray.op_impl_quant import quantize_weight
        from ..ndarray.ndarray import _wrap
        w = float_layer.weight.data(ctx)
        wf = w._data.astype("float32")
        fold_bias = None
        if fold_bn is not None:
            # fold the BN inference affine into the conv (reference
            # mkldnn int8 fuses conv+BN the same way): w' = w*g/sigma
            # per out-channel, b' = beta - mu*g/sigma (+ b*g/sigma)
            import jax.numpy as jnp
            gam = fold_bn.gamma.data(ctx)._data.astype("float32")
            bet = fold_bn.beta.data(ctx)._data.astype("float32")
            mu = fold_bn.running_mean.data(ctx)._data.astype("float32")
            var = fold_bn.running_var.data(ctx)._data.astype("float32")
            bscale = gam / jnp.sqrt(var + fold_bn._epsilon)
            wf = wf * bscale.reshape((-1,) + (1,) * (wf.ndim - 1))
            b0 = (float_layer.bias.data(ctx)._data.astype("float32")
                  if float_layer.bias is not None else 0.0)
            fold_bias = bet - mu * bscale + b0 * bscale
        q, s = quantize_weight(wf, channelwise=channelwise)
        with self.name_scope():
            self.weight_q = self.params.get(
                "weight_q", shape=q.shape, dtype="int8", init="zeros",
                grad_req="null")
            self.weight_scale = self.params.get(
                "weight_scale", shape=s.shape, dtype="float32", init="zeros",
                grad_req="null")
            self.act_amax = self.params.get(
                "act_amax", shape=(1,), dtype="float32", init="zeros",
                grad_req="null")
            self.bias = None
            if fold_bias is not None:
                self.bias = self.params.get(
                    "bias", shape=fold_bias.shape, dtype="float32",
                    init="zeros", grad_req="null")
            elif float_layer.bias is not None:
                self.bias = self.params.get(
                    "bias", shape=float_layer.bias.shape, dtype="float32",
                    init="zeros", grad_req="null")
        self.collect_params().initialize(ctx=ctx)
        self.weight_q.set_data(_wrap(q, ctx))
        self.weight_scale.set_data(_wrap(s, ctx))
        amax = (max(abs(act_range[0]), abs(act_range[1]))
                if act_range is not None else -1.0)  # <=0 → dynamic
        self.act_amax.set_data(nd.array([amax], ctx=ctx))
        if fold_bias is not None:
            from ..ndarray.ndarray import _wrap as _w2
            self.bias.set_data(_w2(fold_bias, ctx))
        elif self.bias is not None:
            self.bias.set_data(float_layer.bias.data(ctx))


class QuantizedDense(_QuantizedBase):
    """int8 replacement for nn.Dense (reference
    quantized_fully_connected): weights pre-quantized, activations
    quantized per call (static range when calibrated)."""

    def __init__(self, float_layer, act_range=None, ctx=None, prefix=None):
        super().__init__(prefix=prefix or (float_layer.name + "_int8_"))
        from ..context import current_context
        ctx = ctx or current_context()
        self._units = float_layer._units
        self._flatten = float_layer._flatten
        self._act = float_layer.act
        self._quantize_weight(float_layer, ctx, act_range)

    def forward(self, x):
        from ..ndarray.register import get_op, invoke
        from ..ndarray.op_impl_quant import quantize_act
        from ..ndarray.ndarray import _wrap
        q, s = quantize_act(x._data, self.act_amax.data(x.ctx)._data)
        bias = self.bias.data(x.ctx) if self.bias is not None else None
        out = invoke(get_op("quantized_fully_connected"),
                     [_wrap(q, x.ctx), self.weight_q.data(x.ctx),
                      _wrap(s, x.ctx), self.weight_scale.data(x.ctx), bias],
                     {"num_hidden": self._units, "flatten": self._flatten,
                      "no_bias": bias is None})
        out = out.astype(x.dtype)
        return self._act(out) if self._act is not None else out


class QuantizedConv2D(_QuantizedBase):
    """int8 replacement for nn.Conv2D (reference quantized_conv)."""

    def __init__(self, float_layer, act_range=None, ctx=None, prefix=None,
                 fold_bn=None):
        super().__init__(prefix=prefix or (float_layer.name + "_int8_"))
        from ..context import current_context
        ctx = ctx or current_context()
        self._kwargs = dict(float_layer._kwargs)
        self._act = float_layer.act
        # s8-interface chaining (quantize_net(s8_interfaces=True) second
        # pass): _out_req holds the NEXT chained conv's act_amax
        # Parameter — the requantize epilogue target; _prequantized
        # means the input arrives already s8 at our own act_amax scale
        self._out_req = None
        self._prequantized = False
        # the float interface dtype of the original model (the layer
        # input dtype is s8 when prequantized — can't cast output to it)
        self._float_dtype = str(float_layer.weight.dtype)
        self._quantize_weight(float_layer, ctx, act_range, fold_bn=fold_bn,
                              channelwise=True)

    def forward(self, x):
        import jax.numpy as jnp
        from ..ndarray.register import get_op, invoke
        from ..ndarray.op_impl_quant import quantize_act, _amax_scale
        from ..ndarray.ndarray import _wrap
        if self._prequantized and str(x.dtype) == "int8":
            # producer already requantized into OUR calibrated scale
            q = x._data
            s = _amax_scale(self.act_amax.data(x.ctx)._data.reshape(())
                            ).reshape(1)
        else:
            q, s = quantize_act(x._data, self.act_amax.data(x.ctx)._data)
        bias = self.bias.data(x.ctx) if self.bias is not None else None
        kw = {k: v for k, v in self._kwargs.items()
              if k in ("kernel", "stride", "dilate", "pad", "num_filter",
                       "num_group")}
        inputs = [_wrap(q, x.ctx), self.weight_q.data(x.ctx),
                  _wrap(s, x.ctx), self.weight_scale.data(x.ctx), bias]
        no_bias = bias is None
        if self._out_req is not None:
            if bias is None:
                # placeholder: invoke only drops TRAILING None inputs
                inputs[4] = _wrap(jnp.zeros((1,), jnp.float32), x.ctx)
            inputs.append(self._out_req.data(x.ctx))
        out = invoke(get_op("quantized_conv"), inputs,
                     {**kw, "no_bias": no_bias})
        if self._out_req is not None:
            # s8 out rides to the chained consumer (relu/Identity
            # between us operate on s8 unchanged). An inline act here
            # would run on raw s8 CODES (wrong for anything nonlinear
            # beyond relu) — the chain pass only links act-free convs.
            assert self._act is None, \
                "s8-interface chain must not carry an inline activation"
            return out
        # keep bf16 interfaces bf16; a prequantized input is s8, so the
        # model's float dtype is the cast target then
        tgt = self._float_dtype if str(x.dtype) == "int8" else x.dtype
        out = out.astype(tgt)
        return self._act(out) if self._act is not None else out


def quantize_net(net, quantized_dtype="int8", calib_data=None,
                 calib_mode="naive", num_calib_examples=32, ctx=None,
                 exclude_layers=(), s8_interfaces=False, **kwargs):
    """Rewrite ``net`` so Dense/Conv2D children execute in int8.

    With ``calib_data``: per-layer INPUT ranges are collected first
    (static activation scales) — ``calib_mode="naive"`` uses running
    min/max, ``"entropy"`` the KL-optimal clipping threshold (reference
    _get_optimal_thresholds), which ignores rare outliers. Without
    calib_data: dynamic per-batch ranges. Returns the same net object
    (rewritten in place), reference-API compatible.

    Conv->BatchNorm pairs inside (Hybrid)Sequential containers are
    folded into the int8 conv (BN dropped); conv weight scales are
    per-out-channel. NOTE: int8 checkpoints written before the
    per-channel change (weight_scale shape (1,)) do not load into
    newly quantized nets."""
    if quantized_dtype != "int8":
        raise MXNetError(f"only int8 is supported, got {quantized_dtype}")
    if s8_interfaces and calib_data is None:
        # validate BEFORE the destructive in-place rewrite — raising
        # after it would leave the caller's net half-quantized
        raise MXNetError(
            "s8_interfaces=True needs calibrated (static) activation "
            "ranges — pass calib_data")
    # hybridized nets would run calibration hooks (which read concrete
    # values) inside a trace, and the cached compiled graph would keep
    # executing the FLOAT layers after the rewrite — deactivate and
    # drop caches first (re-hybridize after quantizing if desired)
    from ..gluon.block import Block as _Block
    if isinstance(net, _Block):
        net.hybridize(active=False)  # recurses; plain Blocks forward it
    ranges = {}
    if calib_data is not None:
        ranges = calib_graph(net, calib_data,
                             num_batches=max(1, num_calib_examples // 32),
                             inputs=True, mode=calib_mode)

    def rewrite(block):
        items = list(block._children.items())
        for idx, (name, child) in enumerate(items):
            rewrite(child)
            if child.name in exclude_layers:
                continue
            if type(child) is _nn.Dense:
                qlayer = QuantizedDense(child, ranges.get(child.name), ctx)
            elif type(child) is _nn.Conv2D:
                # conv immediately followed by BatchNorm in the same
                # container: fold the BN inference affine into the int8
                # conv's weight/bias and drop the BN from the graph
                # (the chain around every conv — dequant->BN->quant —
                # was the measured reason int8 LOST to bf16)
                # fold only where adjacency IS dataflow (Sequential
                # containers), the conv has no inline activation (the
                # float graph is BN(act(conv)) then — folding would
                # reorder to act(BN(conv))), and the BN normalizes the
                # conv out-channel axis
                fold_bn = None
                if isinstance(block, (_nn.Sequential, _nn.HybridSequential)) \
                        and child.act is None \
                        and idx + 1 < len(items) \
                        and type(items[idx + 1][1]) is _nn.BatchNorm \
                        and items[idx + 1][1]._axis == 1 \
                        and items[idx + 1][1].name not in exclude_layers:
                    fold_bn = items[idx + 1][1]
                qlayer = QuantizedConv2D(child, ranges.get(child.name), ctx,
                                         fold_bn=fold_bn)
                if fold_bn is not None:
                    ident = _nn.Identity(prefix=fold_bn.name + "_folded_")
                    block._children[items[idx + 1][0]] = ident
                    for attr, val in list(vars(block).items()):
                        if val is fold_bn:
                            object.__setattr__(block, attr, ident)
            else:
                continue
            block._children[name] = qlayer
            # attribute access (net.fc1) must resolve to the new layer
            for attr, val in list(vars(block).items()):
                if val is child:
                    object.__setattr__(block, attr, qlayer)

    rewrite(net)

    if s8_interfaces:
        _chain_s8_interfaces(net)
    net._quantized_dtype = quantized_dtype
    net._quant_ranges = ranges
    return net


def _chain_s8_interfaces(net):
    """Second rewrite pass: within each (Hybrid)Sequential, when a
    QuantizedConv2D reaches the NEXT QuantizedConv2D through only
    Identity / relu-Activation children, requantize the producer's
    output straight into the consumer's calibrated input scale — the
    tensor between them stays s8 end-to-end (half the bf16 HBM bytes;
    the relu between them is exact on s8: requant-then-relu ==
    relu-then-requant for a symmetric scale). Residual-add boundaries
    (non-Sequential dataflow) stay bf16 — correctness first."""

    def passthrough(child):
        if type(child) is _nn.Identity:
            return True
        return (type(child) is _nn.Activation
                and getattr(child, "_act_type", None) == "relu")

    # chaining mutates the conv INSTANCE (_out_req/_prequantized), so a
    # conv shared by a second dataflow path would return s8 there too —
    # count every block's occurrences across the whole tree and leave
    # any shared instance unchained
    counts = {}

    def count(block):
        for _, c in block._children.items():
            counts[id(c)] = counts.get(id(c), 0) + 1
            count(c)

    count(net)

    def walk(block):
        if isinstance(block, (_nn.Sequential, _nn.HybridSequential)):
            items = [c for _, c in block._children.items()]
            for i, child in enumerate(items):
                if not isinstance(child, QuantizedConv2D):
                    continue
                if child._act is not None:
                    continue  # inline act would run pre-requant
                if counts.get(id(child), 0) > 1:
                    continue  # shared producer: another path needs bf16
                j = i + 1
                while j < len(items) and passthrough(items[j]):
                    j += 1
                if j < len(items) and isinstance(items[j], QuantizedConv2D):
                    consumer = items[j]
                    if counts.get(id(consumer), 0) > 1:
                        continue  # shared consumer: other path feeds bf16
                    amax = float(consumer.act_amax.data().asnumpy()[0])
                    if amax > 0:  # static calibrated range only
                        child._out_req = consumer.act_amax
                        consumer._prequantized = True
        for _, c in block._children.items():
            walk(c)

    walk(net)


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, calib_mode="naive", calib_data=None,
                   num_calib_examples=None, quantized_dtype="int8", **kwargs):
    """Legacy-API entry: returns (sym, arg_params, aux_params) with
    quantization annotations; the executing int8 path is the Gluon
    :func:`quantize_net` rewrite."""
    qsym = sym
    for node in qsym._topo():
        if node._op is not None and node._op.name in ("FullyConnected",
                                                      "Convolution", "dot"):
            node._attrs["__quantized_dtype__"] = quantized_dtype
    return qsym, arg_params, aux_params
