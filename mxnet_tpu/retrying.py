"""One retry/backoff policy for the whole repo.

Three subsystems grew their own ad-hoc retry shapes — the alert-egress
notifier's exponential backoff + jitter (`telemetry/egress.py`), the
dispatch wire's reconnect loop (`serving/wire.py` WireClient), and the
loadgen's client-side router failover (`tools/serve_loadgen.py`) —
each with its own off-by-one attempt math and its own (or no) jitter.
This module is the single policy object they all share:

- :class:`RetryPolicy` — exponential backoff with proportional jitter
  and an optional cap; ``retries`` RE-tries means ``retries + 1``
  total attempts (the egress convention, kept). ``sleep``/``rng`` are
  injectable so goldens run on a scripted clock with a seeded rng —
  no real time passes in tests.
- :class:`Reconnector` — the poll-driven shape: a caller that is
  ticked periodically (a health poll, a maintenance loop) asks
  :meth:`Reconnector.ready` whether enough backoff has elapsed to try
  again, and reports :meth:`failed`/:meth:`succeeded`. Repeated
  failures back off per the policy (so a dead peer is not hammered
  every tick); one success resets.

Stdlib-only on purpose: the wire layer imports this before any heavy
dependency exists.
"""
from __future__ import annotations

import random
import time

__all__ = ["RetryPolicy", "Reconnector"]


class RetryPolicy:
    """Exponential backoff + proportional jitter.

    Parameters
    ----------
    retries : number of RE-tries after the first attempt
        (``call`` makes at most ``retries + 1`` attempts).
    backoff_s : base delay before the first retry.
    multiplier : per-retry growth factor (2.0 = classic doubling).
    jitter : proportional jitter — the delay for attempt ``i`` is
        ``d + uniform(0, d * jitter)`` with ``d = backoff_s *
        multiplier**i`` (capped at ``max_backoff_s``). 0 disables.
    max_backoff_s : cap on the pre-jitter delay (None = uncapped).
    sleep / rng : injectable for scripted-clock goldens (``sleep``
        receives the computed delay; ``rng`` needs ``uniform``).
    """

    def __init__(self, retries=4, backoff_s=0.5, multiplier=2.0,
                 jitter=0.5, max_backoff_s=None, sleep=None, rng=None):
        self.retries = max(0, int(retries))
        self.backoff_s = float(backoff_s)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.max_backoff_s = (float(max_backoff_s)
                              if max_backoff_s is not None else None)
        self._sleep = sleep if sleep is not None else time.sleep
        self._rng = rng if rng is not None else random.Random()

    def delay(self, attempt):
        """The backoff before retry number ``attempt`` (0-based):
        ``backoff * multiplier**attempt`` capped, plus up to
        ``jitter`` of itself."""
        d = self.backoff_s * (self.multiplier ** max(0, int(attempt)))
        if self.max_backoff_s is not None:
            d = min(d, self.max_backoff_s)
        if self.jitter > 0:
            d += self._rng.uniform(0, d * self.jitter)
        return d

    def sleep_before_retry(self, attempt):
        """Compute the delay for ``attempt`` and sleep it (via the
        injected sleep). Returns the delay slept."""
        d = self.delay(attempt)
        self._sleep(d)
        return d

    def call(self, fn, retry_on=(Exception,), on_retry=None):
        """Run ``fn()`` with up to ``retries`` retried attempts.
        Between attempts sleeps the backoff; ``on_retry(attempt,
        exc)`` (optional) observes each retry. The final failure
        re-raises — the caller owns what exhaustion means (the egress
        notifier spools, the loadgen sheds)."""
        attempt = 0
        while True:
            try:
                return fn()
            except retry_on as e:
                if attempt >= self.retries:
                    raise
                if on_retry is not None:
                    on_retry(attempt, e)
                self.sleep_before_retry(attempt)
                attempt += 1


class Reconnector:
    """Backoff gate for poll-driven reconnect loops.

    The caller ticks periodically (e.g. a router's 1 s health poll)
    and asks :meth:`ready` whether a reconnect attempt is due; after
    the attempt it reports :meth:`failed` or :meth:`succeeded`.
    Consecutive failures push the next-allowed attempt out per the
    policy's (jittered, capped) delays, so a dead peer costs one
    connect syscall per backoff window instead of one per tick. One
    success resets the ladder. ``clock`` is injectable (monotonic
    seconds) for scripted tests.

    Not thread-safe by design: each instance belongs to exactly one
    maintenance loop (the wire client's poll-thread ``ensure``).
    """

    def __init__(self, policy=None, clock=None):
        self.policy = policy if policy is not None else RetryPolicy(
            retries=0, backoff_s=0.2, max_backoff_s=5.0)
        self._clock = clock if clock is not None else time.monotonic
        self._failures = 0
        self._next_allowed = None     # None = try immediately

    @property
    def failures(self):
        return self._failures

    def ready(self, now=None):
        """True when an attempt is due (first attempt is always
        due)."""
        if self._next_allowed is None:
            return True
        now = self._clock() if now is None else now
        return now >= self._next_allowed

    def failed(self, now=None):
        """Record a failed attempt; schedules the next one."""
        now = self._clock() if now is None else now
        self._next_allowed = now + self.policy.delay(self._failures)
        self._failures += 1

    def succeeded(self):
        """Reset the ladder: the next failure backs off from the
        base delay again."""
        self._failures = 0
        self._next_allowed = None
