"""Execution-engine shim: async dispatch semantics over PJRT.

The reference's dependency engine (src/engine/threaded_engine.{h,cc},
ThreadedEnginePerDevice) exists to give an eager API async execution:
ops return immediately, writes are serialized per-variable, and Python
blocks only at sync points (WaitToRead/WaitForAll). On TPU, PJRT + XLA
already provide exactly this contract — `jax` op dispatch is
asynchronous, each jax.Array is a future, and `block_until_ready` is
WaitToRead. What remains of the engine is therefore thin and lives here:

- a **sync mode** flag — the NaiveEngine analog
  (``MXNET_ENGINE_TYPE=NaiveEngine``): when on, every op blocks at
  dispatch so async bugs/errors surface at the faulting op;
- a bounded registry of in-flight outputs so ``wait_all()`` can
  implement Engine::WaitForAll;
- deferred exception capture: PJRT raises device errors at sync points;
  we translate them at wait()/asnumpy() like the reference re-throws
  worker-thread exceptions at WaitForVar
  (src/engine/threaded_engine.cc OnComplete path,
  tests/python/unittest/test_exc_handling.py).
"""
from __future__ import annotations

import collections
import os
import threading
import weakref

import jax

__all__ = ["Engine", "engine", "set_bulk_size", "bulk"]


class Engine:
    """Singleton engine shim. ``MXNET_ENGINE_TYPE=NaiveEngine`` selects
    fully synchronous dispatch, mirroring the reference env var."""

    def __init__(self):
        self._lock = threading.Lock()
        # weakrefs, unbounded: WaitForAll must cover EVERY in-flight
        # buffer (the old 256-cap deque silently forgot older work);
        # collected arrays cost nothing and are dropped at the next wait
        self._inflight = collections.deque()
        self.sync = os.environ.get("MXNET_ENGINE_TYPE", "") == "NaiveEngine"

    # -- dispatch hooks (called by the op dispatch layer) ------------------
    def on_dispatch(self, arrays):
        """Record op outputs; block immediately in sync mode. Tracers
        (ops running inside a jit trace — hybridize/functionalize) are
        never tracked: they aren't device work, and blocking on one
        later would raise an escaped-tracer error."""
        arrays = [a for a in arrays if not isinstance(a, jax.core.Tracer)]
        if not arrays:
            return
        if self.sync:
            for a in arrays:
                jax.block_until_ready(a)
        else:
            refs = []
            for a in arrays:
                try:
                    refs.append(weakref.ref(a))
                except TypeError:  # non-weakrefable value (scalar)
                    pass
            with self._lock:
                self._inflight.extend(refs)
                # amortized compaction: drop collected buffers so a loop
                # that never calls waitall() can't grow the queue without
                # bound (live work is always kept)
                if len(self._inflight) > 4096:
                    self._inflight = collections.deque(
                        r for r in self._inflight if r() is not None)

    # -- sync points -------------------------------------------------------
    def wait_for_var(self, array):
        """Engine::WaitForVar — block until this buffer is computed."""
        jax.block_until_ready(array)

    def wait_all(self):
        """Engine::WaitForAll — block until all tracked work completes."""
        with self._lock:
            pending = list(self._inflight)
            self._inflight.clear()
        for ref in pending:
            a = ref()
            if a is not None:
                jax.block_until_ready(a)

    def set_sync(self, flag: bool):
        self.sync = bool(flag)


engine = Engine()

# --- bulking (MXNET_EXEC_BULK_EXEC_* analog) -----------------------------
# In the reference, engine op bulking batches many small ops into one
# engine opr to cut scheduling overhead (src/imperative/cached_op.cc
# segments). Under XLA the analog is tracing a region into one jitted
# computation; `hybridize()` is the real mechanism. `bulk` is kept as an
# API-compatible context manager (mx.engine.bulk) that is currently a
# hint only.
_BULK_SIZE = int(os.environ.get("MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN", "15"))


def set_bulk_size(size: int) -> int:
    global _BULK_SIZE
    prev, _BULK_SIZE = _BULK_SIZE, int(size)
    return prev


class bulk:
    """Context manager: `with mx.engine.bulk(16): ...` (compat shim)."""

    def __init__(self, size: int):
        self.size = size
        self._prev = None

    def __enter__(self):
        self._prev = set_bulk_size(self.size)
        return self

    def __exit__(self, *exc):
        set_bulk_size(self._prev)
        return False


def chain_steps(step_fn, k, donate_argnums=()):
    """Compile ``k`` iterations of a training step into ONE executable —
    the TPU-native realization of the reference engine's op bulking /
    async dispatch pipelining (src/engine/threaded_engine.h: the host
    enqueues ahead so per-op scheduling overhead never serializes with
    device compute; MXNET_EXEC_BULK_EXEC_* batches small ops into one
    engine opr for the same reason).

    Under PJRT each dispatch is one host→device round trip; on a
    remote-attached accelerator that latency (ms-scale) serializes
    between steps. ``chain_steps`` rolls the step into ``lax.scan`` so
    the device runs ``k`` steps back-to-back per dispatch — measured on
    the v5e ResNet-50 config this recovers the entire dispatch gap
    (xprof: 47.0 ms device-busy vs 53.1 ms wall per step at k=1).

    ``step_fn(carry..., *args) -> (carry..., loss)`` must take and
    return the same number of leading carry arrays (params, opt state,
    any number of them); trailing ``args`` are rebroadcast to every
    sub-step. The carry arity is derived from the step's own output
    (len(outputs) - 1 via jax.eval_shape) — no assumption about which
    args are donated. Returns a jitted
    ``fn(carry..., *args) -> (carry..., last_loss)``.
    """
    import jax

    def chained(*all_args):
        out_shapes = jax.eval_shape(step_fn, *all_args)
        if not isinstance(out_shapes, (tuple, list)) or len(out_shapes) < 2:
            raise TypeError(
                "chain_steps: step_fn must return (carry..., loss) with "
                f"at least one carry output, got {type(out_shapes)}")
        n_carry = len(out_shapes) - 1
        if n_carry > len(all_args):
            raise TypeError(
                f"chain_steps: step_fn returns {n_carry} carry outputs "
                f"but was called with only {len(all_args)} arguments")
        rest = all_args[n_carry:]

        def body(carry, _):
            out = step_fn(*carry, *rest)
            return tuple(out[:-1]), out[-1]

        carry, losses = jax.lax.scan(body, tuple(all_args[:n_carry]),
                                     None, length=k)
        return (*carry, losses[-1])

    return jax.jit(chained, donate_argnums=donate_argnums)
