"""Executor — bound symbolic computation.

Analog of the reference GraphExecutor (src/executor/graph_executor.cc)
+ python/mxnet/executor.py. Bind-time compilation parity: ``forward``
traces the whole Symbol DAG into ONE jitted XLA computation per
(shapes, dtypes, training) key — the SimpleBind memory-plan/compile
analog — and dispatches it through the op layer so autograd tapes the
single fused computation (its pullback is the compiled backward graph).
XLA's fusion + buffer planner replace nnvm PlanMemory; set
``MXNET_TPU_SYMBOLIC_JIT=0`` to fall back to the eager per-op DAG walk
(the NaiveEngine-style debug ladder).
"""
from __future__ import annotations


from . import envvars
from .base import MXNetError
from .context import current_context

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None, _graph_cache=None):
        from . import ndarray as nd

        self._symbol = symbol
        self._ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        self.arg_dict = dict(args)
        self.arg_arrays = [self.arg_dict.get(n) for n in arg_names]
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        self.grad_req = grad_req if isinstance(grad_req, dict) else \
            {n: grad_req for n in arg_names}
        self.grad_dict = dict(args_grad) if args_grad else {}
        for n in arg_names:
            req = self.grad_req.get(n, "null")
            if req != "null" and n not in self.grad_dict and n in self.arg_dict:
                self.grad_dict[n] = nd.zeros_like(self.arg_dict[n])
        self.grad_arrays = [self.grad_dict.get(n) for n in arg_names]
        self.aux_dict = dict(aux_states or {})
        self.aux_arrays = list(self.aux_dict.values())
        self.outputs = []
        self._monitor_callback = None
        self._recording = False
        self._jit = envvars.get("MXNET_TPU_SYMBOLIC_JIT")
        # (shape/dtype/training key) -> Op wrapping the jitted graph fn;
        # shared across reshape()-derived executors (BucketingModule: one
        # compiled computation per bucket, nothing re-allocated)
        self._graph_cache = _graph_cache if _graph_cache is not None else {}

    def _graph_op(self, names, arrays, training):
        """The compiled-graph Op for this binding signature."""
        import jax

        from .ndarray.register import Op
        from . import random as _random
        from . import autograd

        from .ndarray.register import dispatch_cast_generation
        key = (training, dispatch_cast_generation(),  # AMP state
               tuple((n, tuple(a.shape), str(a.dtype))
                     for n, a in zip(names, arrays)))
        op = self._graph_cache.get(key)
        if op is not None:
            return op
        # binding compiles: persist the executable across processes
        # (same whole-graph key → disk hit instead of a re-trace+build)
        from . import compile_cache
        compile_cache.ensure()
        sym = self._symbol
        nm = tuple(names)

        def graph_fn(rng_key, *arrs):
            _random.push_trace_key(rng_key)
            prev_t = autograd.set_training(training)
            prev_r = autograd.set_recording(False)
            try:
                outs = sym._eval_raw(dict(zip(nm, arrs)))
            finally:
                autograd.set_recording(prev_r)
                autograd.set_training(prev_t)
                _random.pop_trace_key()
            return tuple(outs)

        op = Op(f"GraphExecutor_{sym.name or 'sym'}", jax.jit(graph_fn),
                differentiable=True)
        self._graph_cache[key] = op
        return op

    @property
    def symbol(self):
        return self._symbol

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    def forward(self, is_train=False, **kwargs):
        from . import autograd

        for k, v in kwargs.items():
            if k in self.arg_dict:
                v.copyto(self.arg_dict[k])
            else:
                self.arg_dict[k] = v
        # attach grads for backward
        if is_train:
            for n, req in self.grad_req.items():
                if req != "null" and n in self.arg_dict:
                    arr = self.arg_dict[n]
                    arr._grad = self.grad_dict.get(n)
                    arr._grad_req = req
                    arr._is_leaf = True
        if self._jit:
            self.outputs = self._forward_jit(is_train)
        elif is_train:
            with autograd.record(train_mode=True):
                self.outputs = self._symbol._eval(self.arg_dict, training=True)
        else:
            with autograd.pause(train_mode=False):
                self.outputs = self._symbol._eval(self.arg_dict, training=False)
        self._recording = is_train
        if self._monitor_callback is not None:
            for name, out in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, out)
        return self.outputs

    def _forward_jit(self, is_train):
        """One invoke of the compiled whole-graph op: the hot loop does a
        single dispatch per step (reference: bulked opr segments of
        GraphExecutor::RunOps), and the autograd tape holds its compiled
        pullback as the backward graph."""
        from . import autograd
        from . import random as _random
        from .ndarray.ndarray import _wrap
        from .ndarray.register import invoke

        bindings = {**self.arg_dict, **self.aux_dict}
        names = list(bindings.keys())
        arrays = [bindings[n] for n in names]
        op = self._graph_op(names, [a._data for a in arrays], bool(is_train))
        rng = _wrap(_random._next_key(), self._ctx)
        scope = autograd.record(train_mode=True) if is_train \
            else autograd.pause(train_mode=False)
        with scope:
            outs = invoke(op, [rng] + arrays, {}, ctx=self._ctx)
        return outs if isinstance(outs, list) else [outs]

    def backward(self, out_grads=None, is_train=True):
        from . import autograd
        from .ndarray import NDArray

        if not self._recording:
            raise MXNetError("backward called without forward(is_train=True)")
        if out_grads is None:
            heads = self.outputs
            head_grads = None
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = self.outputs
            head_grads = out_grads
        autograd.backward(heads, head_grads)
        self._recording = False

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        from . import ndarray as nd
        new_args = {}
        for n, arr in self.arg_dict.items():
            if n in kwargs:
                new_args[n] = nd.zeros(kwargs[n], ctx=self._ctx, dtype=arr.dtype)
            else:
                new_args[n] = arr
        # share the compiled-graph cache: a BucketingModule switching
        # shapes per batch reuses one compiled computation per bucket and
        # keeps existing grad buffers for unchanged shapes
        return Executor(self._symbol, self._ctx, new_args,
                        {n: (self.grad_dict[n]
                             if n in self.grad_dict and n not in kwargs
                             else nd.zeros_like(a))
                         for n, a in new_args.items()
                         if self.grad_req.get(n, "null") != "null"},
                        self.grad_req, self.aux_dict,
                        _graph_cache=self._graph_cache)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError(f"Found name \"{name}\" that is not in the arguments")
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    arr.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise MXNetError(f"Found name \"{name}\" that is not in the auxiliary states")

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))
