"""Executor — bound symbolic computation.

Analog of the reference GraphExecutor (src/executor/graph_executor.cc)
+ python/mxnet/executor.py. Where the reference runs nnvm passes
(InferShape/PlanMemory/attach_op_execs) at bind time and pushes cached
opr segments to the engine per call, here ``forward`` evaluates the
Symbol DAG through the imperative dispatch layer under the autograd
tape, and ``backward`` replays it — XLA's async dispatch + fusion play
the role of the engine + memory planner. (The jit-compiled whole-graph
path lives in Gluon ``hybridize``/CachedOp, matching the reference
split between Module and Gluon.)
"""
from __future__ import annotations

from .base import MXNetError
from .context import current_context

__all__ = ["Executor"]


class Executor:
    def __init__(self, symbol, ctx, args, args_grad=None, grad_req="write",
                 aux_states=None):
        from . import ndarray as nd

        self._symbol = symbol
        self._ctx = ctx or current_context()
        arg_names = symbol.list_arguments()
        if isinstance(args, (list, tuple)):
            args = dict(zip(arg_names, args))
        self.arg_dict = dict(args)
        self.arg_arrays = [self.arg_dict.get(n) for n in arg_names]
        if isinstance(args_grad, (list, tuple)):
            args_grad = dict(zip(arg_names, args_grad))
        self.grad_req = grad_req if isinstance(grad_req, dict) else \
            {n: grad_req for n in arg_names}
        self.grad_dict = dict(args_grad) if args_grad else {}
        for n in arg_names:
            req = self.grad_req.get(n, "null")
            if req != "null" and n not in self.grad_dict and n in self.arg_dict:
                self.grad_dict[n] = nd.zeros_like(self.arg_dict[n])
        self.grad_arrays = [self.grad_dict.get(n) for n in arg_names]
        self.aux_dict = dict(aux_states or {})
        self.aux_arrays = list(self.aux_dict.values())
        self.outputs = []
        self._monitor_callback = None
        self._recording = False

    @property
    def symbol(self):
        return self._symbol

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback

    def forward(self, is_train=False, **kwargs):
        from . import autograd

        for k, v in kwargs.items():
            if k in self.arg_dict:
                v.copyto(self.arg_dict[k])
            else:
                self.arg_dict[k] = v
        # attach grads for backward
        if is_train:
            for n, req in self.grad_req.items():
                if req != "null" and n in self.arg_dict:
                    arr = self.arg_dict[n]
                    arr._grad = self.grad_dict.get(n)
                    arr._grad_req = req
                    arr._is_leaf = True
            with autograd.record(train_mode=True):
                self.outputs = self._symbol._eval(self.arg_dict, training=True)
            self._recording = True
        else:
            with autograd.pause(train_mode=False):
                self.outputs = self._symbol._eval(self.arg_dict, training=False)
            self._recording = False
        if self._monitor_callback is not None:
            for name, out in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, out)
        return self.outputs

    def backward(self, out_grads=None, is_train=True):
        from . import autograd
        from .ndarray import NDArray

        if not self._recording:
            raise MXNetError("backward called without forward(is_train=True)")
        if out_grads is None:
            heads = self.outputs
            head_grads = None
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            heads = self.outputs
            head_grads = out_grads
        autograd.backward(heads, head_grads)
        self._recording = False

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        from . import ndarray as nd
        new_args = {}
        for n, arr in self.arg_dict.items():
            if n in kwargs:
                new_args[n] = nd.zeros(kwargs[n], ctx=self._ctx, dtype=arr.dtype)
            else:
                new_args[n] = arr
        return Executor(self._symbol, self._ctx, new_args,
                        {n: nd.zeros_like(a) for n, a in new_args.items()
                         if self.grad_req.get(n, "null") != "null"},
                        self.grad_req, self.aux_dict)

    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, arr in arg_params.items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError(f"Found name \"{name}\" that is not in the arguments")
        if aux_params:
            for name, arr in aux_params.items():
                if name in self.aux_dict:
                    arr.copyto(self.aux_dict[name])
                elif not allow_extra_params:
                    raise MXNetError(f"Found name \"{name}\" that is not in the auxiliary states")

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))
