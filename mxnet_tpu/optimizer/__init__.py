from .optimizer import (
    Optimizer, Updater, get_updater, create, register,
    SGD, NAG, Adam, AdaGrad, AdaDelta, RMSProp, Ftrl, Signum, SGLD, DCASGD,
    LBSGD, LAMB, AdamW, Test,
)

opt = Optimizer  # legacy alias
