"""Optimizers (python/mxnet/optimizer/optimizer.py analog).

Same surface as the reference: an ``Optimizer`` registry, per-parameter
state creation (``create_state``), index-keyed ``update``, lr/wd
multipliers, gradient rescale/clipping, multi-precision (fp32 master
weights for bf16/fp16 params — the mp_sgd path), and an ``Updater``
wrapper that KVStore server-side updates use. The update math itself
dispatches to the fused optimizer ops (ndarray/op_impl_optimizer.py),
which write back through ``out=``: on TPU each update is one XLA
computation per parameter (and Trainer's jitted path fuses whole
buckets).
"""
from __future__ import annotations

import math
import pickle

import numpy as np

from ..base import _Registry, MXNetError
from ..ndarray import NDArray, zeros
from ..ndarray.register import invoke as _invoke, get_op as _get_op

__all__ = ["Optimizer", "Updater", "get_updater", "create", "register"]

_REG = _Registry("optimizer")


def register(klass):
    _REG.register(klass.__name__.lower())(klass)
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REG.get(name)(**kwargs)


class Optimizer:
    """Base optimizer. Subclasses implement create_state + update."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, multi_precision=False,
                 param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        self.multi_precision = multi_precision
        self.idx2name = dict(param_idx2name or {})
        self.param_dict = param_dict or {}
        self.aggregate_num = 0

    # -- registry-compat
    create_optimizer = staticmethod(create)

    def create_state(self, index, weight):
        return None

    def create_state_multi_precision(self, index, weight):
        """fp32 master weight for low-precision params (mp_* ops)."""
        if self.multi_precision and str(weight.dtype) in ("float16", "bfloat16"):
            weight_master_copy = weight.astype("float32")
            return (self.create_state(index, weight_master_copy), weight_master_copy)
        return self.create_state(index, weight)

    def update(self, index, weight, grad, state):
        raise NotImplementedError

    def update_multi_precision(self, index, weight, grad, state):
        if self.multi_precision and str(weight.dtype) in ("float16", "bfloat16"):
            inner_state, weight32 = state
            grad32 = grad.astype("float32")
            self.update(index, weight32, grad32, inner_state)
            weight32.copyto(weight)
        else:
            self.update(index, weight, grad, state)

    def update_multi(self, indices, weights, grads, states):
        """Aggregated update over many parameters — base: a loop;
        optimizers with multi-tensor fused ops (SGD → multi_sgd_*)
        override to one op call (reference aggregate_num path)."""
        for i, w, g, s in zip(indices, weights, grads, states):
            self.update_multi_precision(i, w, g, s)

    # -- lr/wd plumbing (mirrors reference semantics)
    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise MXNetError("lr_scheduler is set; cannot set learning rate directly")
        self.lr = lr

    @property
    def learning_rate(self):
        if self.lr_scheduler is not None:
            return self.lr_scheduler(self.num_update)
        return self.lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = dict(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = dict(args_wd_mult)

    def _update_count(self, index):
        if not isinstance(index, (list, tuple)):
            index = [index]
        for idx in index:
            if idx not in self._index_update_count:
                self._index_update_count[idx] = self.begin_num_update
            self._index_update_count[idx] += 1
            self.num_update = max(self._index_update_count[idx], self.num_update)

    def _get_lr(self, index):
        lr = self.lr_scheduler(self.num_update) if self.lr_scheduler else self.lr
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            lr *= self.param_dict[name].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif name in self.lr_mult:
            lr *= self.lr_mult[name]
        return lr

    def _get_wd(self, index):
        wd = self.wd
        name = self.idx2name.get(index, index)
        if name in self.param_dict:
            wd *= self.param_dict[name].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif name in self.wd_mult:
            wd *= self.wd_mult[name]
        return wd

    def _common_kwargs(self, index):
        kw = {"lr": self._get_lr(index), "wd": self._get_wd(index),
              "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        return kw


@register
class SGD(Optimizer):
    """SGD (+momentum, multi-precision) — sgd_update / sgd_mom_update ops."""

    def __init__(self, momentum=0.0, lazy_update=True, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        from ..ndarray.sparse import RowSparseNDArray, sgd_update_rsp, \
            sgd_mom_update_rsp

        if isinstance(grad, RowSparseNDArray):
            kw.pop("wd_lh", None)
            if state is None:
                sgd_update_rsp(weight, grad, **kw)
            else:
                sgd_mom_update_rsp(weight, grad, state,
                                   momentum=self.momentum,
                                   lazy_update=self.lazy_update, **kw)
            return
        if state is None:
            _invoke(_get_op("sgd_update"), [weight, grad], kw, out=weight)
        else:
            kw["momentum"] = self.momentum
            _invoke(_get_op("sgd_mom_update"), [weight, grad, state], kw, out=weight)

    def update_multi(self, indices, weights, grads, states):
        """ONE fused multi-tensor op over the whole parameter list
        (reference multi_sgd_update/multi_sgd_mom_update — SURVEY §2.1
        optimizer row): one XLA computation, one dispatch, per step."""
        from ..ndarray.sparse import BaseSparseNDArray
        if (self.multi_precision
                or any(isinstance(g, BaseSparseNDArray) for g in grads)
                or any(isinstance(w, BaseSparseNDArray) for w in weights)):
            return super().update_multi(indices, weights, grads, states)
        self._update_count(list(indices))
        lrs = [self._get_lr(i) for i in indices]
        wds = [self._get_wd(i) for i in indices]
        kw = {"lrs": lrs, "wds": wds, "rescale_grad": self.rescale_grad,
              "num_weights": len(indices)}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        if self.momentum == 0.0:
            args = []
            for w, g in zip(weights, grads):
                args += [w, g]
            _invoke(_get_op("multi_sgd_update"), args, kw, out=list(weights))
        else:
            kw["momentum"] = self.momentum
            args = []
            outs = []
            for w, g, m in zip(weights, grads, states):
                args += [w, g, m]
                outs += [w, m]
            _invoke(_get_op("multi_sgd_mom_update"), args, kw, out=outs)


@register
class NAG(SGD):
    # NAG math differs from SGD — no multi_sgd fusion
    update_multi = Optimizer.update_multi

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            _invoke(_get_op("sgd_update"), [weight, grad], kw, out=weight)
        else:
            kw["momentum"] = self.momentum
            _invoke(_get_op("nag_mom_update"), [weight, grad, state], kw, out=weight)


@register
class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lazy_update = lazy_update

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        # bias correction folded into lr (reference Adam does the same)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        kw["lr"] = kw["lr"] * math.sqrt(coef2) / coef1
        kw.update(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon)
        mean, var = state
        from ..ndarray.sparse import RowSparseNDArray, adam_update_rsp

        if isinstance(grad, RowSparseNDArray):
            adam_update_rsp(weight, grad, mean, var,
                            lazy_update=self.lazy_update, **kw)
            return
        _invoke(_get_op("adam_update"), [weight, grad, mean, var], kw, out=weight)


@register
class AdamW(Adam):
    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        kw = self._common_kwargs(index)
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        kw["lr"] = kw["lr"] * math.sqrt(coef2) / coef1
        kw.update(beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon)
        mean, var = state
        _invoke(_get_op("adamw_update"), [weight, grad, mean, var], kw, out=weight)


@register
class AdaGrad(Optimizer):
    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        kw["epsilon"] = self.float_stable_eps
        from ..ndarray.sparse import RowSparseNDArray, adagrad_update_rsp

        if isinstance(grad, RowSparseNDArray):
            adagrad_update_rsp(weight, grad, state, **kw)
            return
        _invoke(_get_op("adagrad_update"), [weight, grad, state], kw, out=weight)


@register
class AdaDelta(Optimizer):
    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = {"wd": self._get_wd(index), "rescale_grad": self.rescale_grad,
              "rho": self.rho, "epsilon": self.epsilon}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        acc_g, acc_delta = state
        _invoke(_get_op("adadelta_update"), [weight, grad, acc_g, acc_delta], kw,
                out=weight)


@register
class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (zeros(weight.shape, weight.ctx, dtype=weight.dtype),
                    zeros(weight.shape, weight.ctx, dtype=weight.dtype),
                    zeros(weight.shape, weight.ctx, dtype=weight.dtype))
        return zeros(weight.shape, weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        kw.update(gamma1=self.gamma1, epsilon=self.epsilon)
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if self.centered:
            n, g, delta = state
            kw["gamma2"] = self.gamma2
            _invoke(_get_op("rmspropalex_update"), [weight, grad, n, g, delta], kw,
                    out=weight)
        else:
            _invoke(_get_op("rmsprop_update"), [weight, grad, state], kw, out=weight)


@register
class Ftrl(Optimizer):
    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        kw.update(lamda1=self.lamda1, beta=self.beta)
        z, n = state
        _invoke(_get_op("ftrl_update"), [weight, grad, z, n], kw, out=weight)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        kw = self._common_kwargs(index)
        if state is None:
            _invoke(_get_op("signsgd_update"), [weight, grad], kw, out=weight)
        else:
            kw.update(momentum=self.momentum, wd_lh=self.wd_lh)
            _invoke(_get_op("signum_update"), [weight, grad, state], kw, out=weight)


@register
class LAMB(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-6, lower_bound=None, upper_bound=None,
                 bias_correction=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.lower_bound, self.upper_bound = lower_bound, upper_bound
        self.bias_correction = bias_correction

    def create_state(self, index, weight):
        return (zeros(weight.shape, weight.ctx, dtype=weight.dtype),
                zeros(weight.shape, weight.ctx, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        t = self._index_update_count[index]
        mean, var = state
        kw = {"beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon,
              "t": t, "bias_correction": self.bias_correction,
              "wd": self._get_wd(index), "rescale_grad": self.rescale_grad}
        if self.clip_gradient is not None:
            kw["clip_gradient"] = self.clip_gradient
        g = _invoke(_get_op("lamb_update_phase1"), [weight, grad, mean, var], kw)
        r1 = weight.norm()
        r2 = g.norm()
        kw2 = {"lr": self._get_lr(index)}
        if self.lower_bound:
            kw2["lower_bound"] = self.lower_bound
        if self.upper_bound:
            kw2["upper_bound"] = self.upper_bound
        _invoke(_get_op("lamb_update_phase2"), [weight, g, r1, r2], kw2, out=weight)


@register
class SGLD(Optimizer):
    """Stochastic Gradient Langevin Dynamics."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        from .. import ndarray as nd
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        noise = nd.random.normal(0, math.sqrt(lr), weight.shape,
                                 dtype=str(weight.dtype), ctx=weight.ctx)
        weight._set_data(
            (weight - lr / 2 * (g + wd * weight) + noise)._data)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, weight.ctx, dtype=weight.dtype), weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        mom, previous_weight = state
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = g.clip(-self.clip_gradient, self.clip_gradient)
        comp = g + self.lamda * g * g * (weight - previous_weight)
        if mom is None:
            new = weight - lr * (comp + wd * weight)
        else:
            mom._set_data((self.momentum * mom - lr * (comp + wd * weight))._data)
            new = weight + mom
        previous_weight._set_data(weight._data)
        weight._set_data(new._data)


@register
class LBSGD(SGD):
    """Large-batch SGD with LARS-style layer-wise scaling (reference LBSGD)."""

    # LARS trust-ratio math differs per layer — no multi_sgd fusion
    update_multi = Optimizer.update_multi

    def __init__(self, momentum=0.0, warmup_strategy="linear",
                 warmup_epochs=5, batch_scale=1, updates_per_epoch=32,
                 begin_epoch=0, num_epochs=60, **kwargs):
        super().__init__(momentum=momentum, **kwargs)
        self.warmup_strategy = warmup_strategy

    def update(self, index, weight, grad, state):
        # LARS trust ratio
        w_norm = float(weight.norm().asscalar())
        g_norm = float((grad * self.rescale_grad).norm().asscalar())
        trust = 1.0
        if w_norm > 0 and g_norm > 0:
            trust = 0.001 * w_norm / (g_norm + self._get_wd(index) * w_norm)
        saved_lr = self.lr
        try:
            if self.lr_scheduler is None:
                self.lr = self.lr * trust
            super().update(index, weight, grad, state)
        finally:
            self.lr = saved_lr


@register
class Test(Optimizer):
    """Trivial optimizer used by reference unit tests."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.ctx, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        weight._set_data((weight + grad * self.rescale_grad)._data)


class Updater:
    """Applies an optimizer by key — used by KVStore server-side updates
    (reference python/mxnet/optimizer/optimizer.py get_updater +
    kvstore server pickling round-trip)."""

    def __init__(self, optimizer: Optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}
        self.aggregate_updates = optimizer.aggregate_num > 0

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state_multi_precision(index, weight)
            self.states_synced[index] = True
        self.optimizer.update_multi_precision(index, weight, grad, self.states[index])

    def update_multi(self, indices, grads, weights):
        """Aggregated entry (Trainer fast path): one fused op for
        optimizers that support it."""
        for index, weight in zip(indices, weights):
            if index not in self.states:
                self.states[index] = \
                    self.optimizer.create_state_multi_precision(index, weight)
                self.states_synced[index] = True
        self.optimizer.update_multi(indices, weights, grads,
                                    [self.states[i] for i in indices])

    def set_states(self, states):
        payload = pickle.loads(states)
        if isinstance(payload, tuple) and len(payload) == 2:
            second = payload[1]
            if isinstance(second, Optimizer):
                # dump_optimizer=True payload: the optimizer itself
                # (with its schedules/num_update) rides along
                self.states, self.optimizer = payload
            else:
                self.states, self.optimizer.num_update = payload
        else:
            self.states = payload
        self.states_synced = {k: False for k in self.states}

    def get_states(self, dump_optimizer=False):
        return pickle.dumps(
            (self.states, self.optimizer.num_update) if not dump_optimizer
            else (self.states, self.optimizer))


def get_updater(optimizer: Optimizer) -> Updater:
    return Updater(optimizer)
