"""Symbolic RNN cells (reference python/mxnet/rnn/rnn_cell.py).

Each cell's ``__call__(inputs, states) -> (output, next_states)``
composes Symbol ops (FullyConnected + Activation + elementwise), and
``unroll`` builds the length-T graph — compiled as ONE XLA program by
the symbolic executor, so the reference's per-step engine dispatch
becomes a fused computation per bucket length (BucketingModule pairs
with this exactly as upstream).
"""
from __future__ import annotations

from .. import symbol as sym
from ..base import MXNetError

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ResidualCell", "BidirectionalCell"]


class RNNParams:
    """Container sharing weight Symbols across time steps (reference
    rnn_cell.py RNNParams)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = sym.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """Abstract cell: subclasses define state_info and __call__."""

    def __init__(self, prefix="", params=None):
        self._prefix = prefix
        self._own_params = params is None
        self.params = params if params is not None else RNNParams(prefix)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [info["shape"] for info in self.state_info]

    def begin_state(self, func=None, batch_size=None, **kwargs):
        """Initial state symbols.

        Default (func=None): free Variables — bind them with concrete
        shapes. With ``func`` (e.g. ``mx.sym.zeros``): the reference
        leaves batch as 0 and relies on nnvm's bidirectional shape
        inference; the XLA forward-only inference can't resolve that, so
        pass ``batch_size`` to substitute it (or omit begin_state
        entirely in ``unroll`` — the default builds zeros tied to the
        input's batch dim symbolically)."""
        self._init_counter += 1
        states = []
        for i, info in enumerate(self.state_info):
            name = f"{self._prefix}begin_state_{self._init_counter}_{i}"
            if func is None:
                states.append(sym.Variable(name, **kwargs))
            else:
                info = dict(info)
                shape = tuple(info.pop("shape", ()))
                if batch_size is not None:
                    shape = tuple(batch_size if d == 0 else d for d in shape)
                if any(d == 0 for d in shape):
                    raise MXNetError(
                        "begin_state(func=...) needs a concrete batch: "
                        "pass batch_size=N (the reference resolves the "
                        "0 dim via nnvm bidirectional inference, which "
                        "the forward-only XLA walk does not do)")
                info.pop("__layout__", None)
                states.append(func(name=name, shape=shape, **info, **kwargs))
        return states

    def __call__(self, inputs, states):
        raise NotImplementedError

    def _normalize_inputs(self, length, inputs, layout):
        """One Symbol (split on the layout's T axis) or a per-step list
        -> validated per-step list (shared by every unroll)."""
        axis = layout.find("T")
        if axis < 0:
            raise MXNetError(f"invalid layout {layout!r}")
        if not isinstance(inputs, (list, tuple)):
            splitted = sym.split(inputs, num_outputs=length, axis=axis,
                                 squeeze_axis=True)
            inputs = [splitted[i] for i in range(length)]
        if len(inputs) != length:
            raise MXNetError(
                f"got {len(inputs)} step inputs, expected {length}")
        return list(inputs)

    def _zero_state_like(self, step_input):
        """Zero initial states derived from one step input symbol
        (keeps the batch dimension symbolically tied to the data)."""
        states = []
        for info in self.state_info:
            h = int(info["shape"][-1])
            states.append(sym.broadcast_to(
                sym.sum(step_input, axis=-1, keepdims=True) * 0.0,
                shape=(0, h)))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell ``length`` steps (reference BaseRNNCell.unroll).

        inputs: one Symbol (sliced along the time axis of ``layout``) or
        a list of per-step Symbols. Returns (outputs, states) where
        outputs is a single concatenated Symbol when merge_outputs else
        the per-step list.
        """
        self.reset()
        axis = layout.find("T")
        inputs = self._normalize_inputs(length, inputs, layout)
        if begin_state is None:
            # default: ZERO states built symbolically FROM the input
            # (batch dim rides along), so the unrolled graph is fully
            # forward-shape-inferable — the reference leaves free
            # variables here and relies on nnvm's bidirectional
            # inference, which the XLA eval_shape walk doesn't do. To
            # feed initial states, pass begin_state=cell.begin_state()
            # variables explicitly and bind them with shapes.
            states = self._zero_state_like(inputs[0])
        else:
            states = begin_state
        outputs = []
        for t in range(length):
            out, states = self(inputs[t], states)
            outputs.append(out)
        if merge_outputs:
            expanded = [sym.expand_dims(o, axis=axis) for o in outputs]
            return sym.concat(*expanded, dim=axis), states
        return outputs, states


class RNNCell(BaseRNNCell):
    """Vanilla tanh cell (reference rnn_cell.py RNNCell)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=self._num_hidden,
                                 name=f"{name}h2h")
        output = sym.Activation(i2h + h2h, act_type=self._activation,
                                name=f"{name}out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell (reference rnn_cell.py LSTMCell; gate order i,f,c,o —
    the cuDNN-canonical order the fused RNN op also uses)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        # forget_bias is BAKED INTO the h2h_bias initializer (reference
        # rnn_cell.py: init.LSTMBias), NOT added at runtime — trained
        # checkpoints then interchange with the reference bit-for-bit
        from ..initializer import LSTMBias
        self._hB = self.params.get(
            "h2h_bias", init=LSTMBias(forget_bias=forget_bias))

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=4 * self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=4 * self._num_hidden,
                                 name=f"{name}h2h")
        gates = i2h + h2h
        sliced = sym.SliceChannel(gates, num_outputs=4, name=f"{name}slice")
        in_gate, forget_gate, in_trans, out_gate = (sliced[i]
                                                    for i in range(4))
        in_gate = sym.Activation(in_gate, act_type="sigmoid")
        forget_gate = sym.Activation(forget_gate, act_type="sigmoid")
        in_trans = sym.Activation(in_trans, act_type="tanh")
        out_gate = sym.Activation(out_gate, act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_trans
        next_h = out_gate * sym.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell (reference rnn_cell.py GRUCell; gate order r,z,n)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = f"{self._prefix}t{self._counter}_"
        i2h = sym.FullyConnected(inputs, self._iW, self._iB,
                                 num_hidden=3 * self._num_hidden,
                                 name=f"{name}i2h")
        h2h = sym.FullyConnected(states[0], self._hW, self._hB,
                                 num_hidden=3 * self._num_hidden,
                                 name=f"{name}h2h")
        si = sym.SliceChannel(i2h, num_outputs=3, name=f"{name}i2h_slice")
        sh = sym.SliceChannel(h2h, num_outputs=3, name=f"{name}h2h_slice")
        i_r, i_z, i_n = (si[i] for i in range(3))
        h_r, h_z, h_n = (sh[i] for i in range(3))
        reset = sym.Activation(i_r + h_r, act_type="sigmoid")
        update = sym.Activation(i_z + h_z, act_type="sigmoid")
        new = sym.Activation(i_n + reset * h_n, act_type="tanh")
        next_h = update * states[0] + (1.0 - update) * new
        return next_h, [next_h]


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in sequence per step (reference
    SequentialRNNCell)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)

    def reset(self):
        super().reset()
        for c in getattr(self, "_cells", []):
            c.reset()

    @property
    def state_info(self):
        return sum((c.state_info for c in self._cells), [])

    def begin_state(self, func=None, **kwargs):
        return sum((c.begin_state(func=func, **kwargs)
                    for c in self._cells), [])

    def __call__(self, inputs, states):
        next_states = []
        pos = 0
        out = inputs
        for cell in self._cells:
            n = len(cell.state_info)
            out, ns = cell(out, states[pos:pos + n])
            next_states.extend(ns)
            pos += n
        return out, next_states


class DropoutCell(BaseRNNCell):
    """Stateless dropout between stacked cells (reference DropoutCell)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self._dropout > 0:
            inputs = sym.Dropout(inputs, p=self._dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (reference ModifierCell)."""

    def __init__(self, base_cell):
        super().__init__(prefix=base_cell._prefix, params=base_cell.params)
        self.base_cell = base_cell

    def reset(self):
        super().reset()
        if hasattr(self, "base_cell"):
            self.base_cell.reset()

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        return self.base_cell.begin_state(func=func, **kwargs)


class ResidualCell(ModifierCell):
    """Adds the step input to the cell output (reference ResidualCell)."""

    def __call__(self, inputs, states):
        out, states = self.base_cell(inputs, states)
        return out + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        # delegate so unroll-only wrapped cells (BidirectionalCell)
        # compose, as upstream ResidualCell.unroll does
        self.reset()
        axis = layout.find("T")
        inputs = self._normalize_inputs(length, inputs, layout)
        outputs, states = self.base_cell.unroll(
            length, inputs, begin_state=begin_state, layout=layout,
            merge_outputs=False)
        outputs = [o + i for o, i in zip(outputs, inputs)]
        if merge_outputs:
            outputs = sym.concat(*[sym.expand_dims(o, axis=axis)
                                   for o in outputs], dim=axis)
        return outputs, states


class BidirectionalCell(BaseRNNCell):
    """Runs one cell forward and one backward over the sequence,
    concatenating per-step outputs (reference BidirectionalCell).
    Stepwise ``__call__`` is undefined for a bidirectional cell — use
    ``unroll`` (the reference raises the same way)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        # params is accepted for reference-signature parity; the wrapped
        # cells own their parameters
        super().__init__(prefix=output_prefix, params=params)
        self._l = l_cell
        self._r = r_cell

    def reset(self):
        super().reset()
        if hasattr(self, "_l"):
            self._l.reset()
            self._r.reset()

    @property
    def state_info(self):
        return self._l.state_info + self._r.state_info

    def begin_state(self, func=None, **kwargs):
        return (self._l.begin_state(func=func, **kwargs)
                + self._r.begin_state(func=func, **kwargs))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        inputs = self._normalize_inputs(length, inputs, layout)
        nl = len(self._l.state_info)
        if begin_state is None:
            l_states = r_states = None
        else:
            l_states, r_states = begin_state[:nl], begin_state[nl:]
        l_out, l_states = self._l.unroll(length, list(inputs),
                                         begin_state=l_states, layout=layout,
                                         merge_outputs=False)
        r_out, r_states = self._r.unroll(length, list(reversed(inputs)),
                                         begin_state=r_states, layout=layout,
                                         merge_outputs=False)
        outputs = [sym.concat(lo, ro, dim=1)
                   for lo, ro in zip(l_out, reversed(r_out))]
        if merge_outputs:
            outputs = sym.concat(*[sym.expand_dims(o, axis=axis)
                                   for o in outputs], dim=axis)
        return outputs, l_states + r_states
