"""Legacy symbolic RNN cell API (reference python/mxnet/rnn/rnn_cell.py).

Pre-Gluon cells that build SYMBOL graphs — the API behind the
reference's bucketing examples (example/rnn/bucketing with
BucketingModule). Gluon models should use ``gluon.rnn``; this namespace
exists so reference scripts using ``mx.rnn.LSTMCell(...).unroll(...)``
port unchanged.
"""
from .rnn_cell import (BaseRNNCell, RNNCell, LSTMCell, GRUCell,
                       SequentialRNNCell, DropoutCell, RNNParams,
                       ModifierCell, ResidualCell, BidirectionalCell)

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "DropoutCell", "RNNParams",
           "ModifierCell", "ResidualCell", "BidirectionalCell"]
