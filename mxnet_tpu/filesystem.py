"""URI-routed filesystem layer — the dmlc-core filesystem abstraction.

Reference analog: ``3rdparty/dmlc-core/src/io/`` (`LocalFileSystem`,
`S3FileSystem`, `HDFSFileSystem` behind ``dmlc::Stream::Create`` URI
routing) — the layer that lets every reference IO surface (RecordIO,
NDArray save/load, checkpoints) read ``s3://...`` the same way it reads
a local path. TPU-native design: a small scheme registry instead of
C++ virtual streams; schemes are pluggable so cloud backends register
without the core importing their SDKs.

Built-in schemes:

- local paths (no scheme, or ``file://``) — plain ``open``;
- ``memory://`` — an in-process byte store (the dmlc ``MemoryFileSystem``
  test backend; also handy for CI without a writable disk).

``s3://`` / ``hdfs:// `` / ``gs://`` raise a clear error unless a
handler is registered with :func:`register_scheme` (this build runs in
a zero-egress environment — shipping stub clients that cannot work
would be worse than an honest error naming the extension point).
"""
from __future__ import annotations

import io
import os
import threading

from .base import MXNetError

__all__ = ["open_uri", "exists", "list_prefix", "register_scheme",
           "MemoryFileSystem"]

_LOCK = threading.Lock()


def _split_scheme(uri):
    if "://" in str(uri):
        scheme, rest = str(uri).split("://", 1)
        return scheme.lower(), rest
    return "", str(uri)


class _MemWriter(io.BytesIO):
    def __init__(self, store, path, initial=b""):
        super().__init__()
        self._store = store
        self._path = path
        if initial:
            self.write(initial)

    def close(self):
        if not self.closed:  # idempotent, like real file objects
            self._store[self._path] = self.getvalue()
        super().close()


class _MemTextWriter(io.StringIO):
    def __init__(self, store, path, initial=""):
        super().__init__()
        self._store = store
        self._path = path
        if initial:
            self.write(initial)

    def close(self):
        if not self.closed:
            self._store[self._path] = self.getvalue().encode()
        super().close()


class MemoryFileSystem:
    """In-process byte store behind ``memory://`` URIs."""

    def __init__(self):
        self._files: dict[str, bytes] = {}

    def open(self, path, mode):
        if "+" in mode:
            raise MXNetError(
                f"memory:// does not support update mode {mode!r}")
        if "r" in mode:
            if path not in self._files:
                raise FileNotFoundError(f"memory://{path}")
            data = self._files[path]
            return io.BytesIO(data) if "b" in mode \
                else io.StringIO(data.decode())
        initial = self._files.get(path, b"") if "a" in mode else b""
        if "b" in mode:
            return _MemWriter(self._files, path, initial)
        return _MemTextWriter(self._files, path, initial.decode())

    def exists(self, path):
        return path in self._files

    def list(self, prefix):
        return sorted(p for p in self._files if p.startswith(prefix))

    def clear(self):
        self._files.clear()


_MEMORY = MemoryFileSystem()

_SCHEMES: dict = {}


def register_scheme(scheme, opener, exists_fn=None, list_fn=None):
    """Register a URI scheme handler.

    ``opener(path, mode) -> file-like``; optional ``exists_fn(path)``
    and ``list_fn(prefix) -> [path, ...]`` (sharded-checkpoint
    discovery needs listing). This is how an S3/HDFS/GCS client plugs
    in (dmlc registered its cloud filesystems the same way).
    """
    with _LOCK:
        _SCHEMES[scheme.lower()] = (opener, exists_fn, list_fn)


register_scheme("memory", _MEMORY.open, _MEMORY.exists, _MEMORY.list)


def open_uri(uri, mode="rb"):
    """Open ``uri`` — local path, ``file://``, ``memory://`` or any
    registered scheme (dmlc ``Stream::Create`` analog)."""
    scheme, path = _split_scheme(uri)
    if scheme in ("", "file"):
        return open(path, mode)
    with _LOCK:
        entry = _SCHEMES.get(scheme)
    if entry is None:
        raise MXNetError(
            f"no filesystem registered for scheme {scheme!r} (uri {uri!r}); "
            "register one with mxnet_tpu.filesystem.register_scheme — "
            "cloud filesystems (s3/hdfs/gs) need their client installed "
            "and registered, this environment has no network egress")
    return entry[0](path, mode)


def _scheme_entry(scheme, uri, capability, idx):
    with _LOCK:
        entry = _SCHEMES.get(scheme)
    if entry is None:
        raise MXNetError(
            f"no filesystem registered for scheme {scheme!r} (uri {uri!r}); "
            "register one with mxnet_tpu.filesystem.register_scheme")
    if entry[idx] is None:
        # a silent False/[] would make existence-gated loads skip REAL
        # data — signal the capability gap instead
        raise MXNetError(
            f"filesystem for scheme {scheme!r} registered no "
            f"{capability} handler (uri {uri!r})")
    return entry[idx]


def exists(uri):
    scheme, path = _split_scheme(uri)
    if scheme in ("", "file"):
        return os.path.exists(path)
    return _scheme_entry(scheme, uri, "exists", 1)(path)


def list_prefix(uri_prefix):
    """All URIs under a prefix (sharded-checkpoint discovery; the
    local scheme globs ``prefix*``)."""
    scheme, path = _split_scheme(uri_prefix)
    if scheme in ("", "file"):
        import glob as _glob
        return sorted(_glob.glob(path + "*"))
    lister = _scheme_entry(scheme, uri_prefix, "list", 2)
    return [f"{scheme}://{p}" for p in lister(path)]
