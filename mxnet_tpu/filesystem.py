"""URI-routed filesystem layer — the dmlc-core filesystem abstraction.

Reference analog: ``3rdparty/dmlc-core/src/io/`` (`LocalFileSystem`,
`S3FileSystem`, `HDFSFileSystem` behind ``dmlc::Stream::Create`` URI
routing) — the layer that lets every reference IO surface (RecordIO,
NDArray save/load, checkpoints) read ``s3://...`` the same way it reads
a local path. TPU-native design: a small scheme registry instead of
C++ virtual streams; schemes are pluggable so cloud backends register
without the core importing their SDKs.

Built-in schemes:

- local paths (no scheme, or ``file://``) — plain ``open``;
- ``memory://`` — an in-process byte store (the dmlc ``MemoryFileSystem``
  test backend; also handy for CI without a writable disk).

``s3://`` / ``hdfs:// `` / ``gs://`` raise a clear error unless a
handler is registered with :func:`register_scheme` (this build runs in
a zero-egress environment — shipping stub clients that cannot work
would be worse than an honest error naming the extension point).
"""
from __future__ import annotations

import io
import os
import threading

from .base import MXNetError

__all__ = ["open_uri", "exists", "register_scheme", "MemoryFileSystem"]

_LOCK = threading.Lock()


def _split_scheme(uri):
    if "://" in str(uri):
        scheme, rest = str(uri).split("://", 1)
        return scheme.lower(), rest
    return "", str(uri)


class MemoryFileSystem:
    """In-process byte store behind ``memory://`` URIs."""

    def __init__(self):
        self._files: dict[str, bytes] = {}

    def open(self, path, mode):
        if "r" in mode:
            if path not in self._files:
                raise FileNotFoundError(f"memory://{path}")
            data = self._files[path]
            return io.BytesIO(data) if "b" in mode \
                else io.StringIO(data.decode())
        store = self._files

        class _Writer(io.BytesIO if "b" in mode else io.StringIO):
            def close(self2):
                val = self2.getvalue()
                store[path] = val if isinstance(val, bytes) else val.encode()
                super(type(self2), self2).close()

            def __exit__(self2, *exc):
                self2.close()

        return _Writer()

    def exists(self, path):
        return path in self._files

    def clear(self):
        self._files.clear()


_MEMORY = MemoryFileSystem()

_SCHEMES: dict = {}


def register_scheme(scheme, opener, exists_fn=None):
    """Register a URI scheme handler.

    ``opener(path, mode) -> file-like``; optional ``exists_fn(path)``.
    This is how an S3/HDFS/GCS client plugs in (dmlc registered its
    cloud filesystems the same way at build time).
    """
    with _LOCK:
        _SCHEMES[scheme.lower()] = (opener, exists_fn)


register_scheme("memory", _MEMORY.open, _MEMORY.exists)


def open_uri(uri, mode="rb"):
    """Open ``uri`` — local path, ``file://``, ``memory://`` or any
    registered scheme (dmlc ``Stream::Create`` analog)."""
    scheme, path = _split_scheme(uri)
    if scheme in ("", "file"):
        return open(path, mode)
    with _LOCK:
        entry = _SCHEMES.get(scheme)
    if entry is None:
        raise MXNetError(
            f"no filesystem registered for scheme {scheme!r} (uri {uri!r}); "
            "register one with mxnet_tpu.filesystem.register_scheme — "
            "cloud filesystems (s3/hdfs/gs) need their client installed "
            "and registered, this environment has no network egress")
    return entry[0](path, mode)


def exists(uri):
    scheme, path = _split_scheme(uri)
    if scheme in ("", "file"):
        return os.path.exists(path)
    with _LOCK:
        entry = _SCHEMES.get(scheme)
    if entry is None or entry[1] is None:
        return False
    return entry[1](path)
