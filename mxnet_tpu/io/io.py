"""Data iterators (python/mxnet/io/io.py + src/io/ analog).

The reference's C++ iterator stack (MXDataIter over
iter_image_recordio_2.cc decode/augment workers + PrefetcherIter +
BatchLoader) is re-designed for TPU as: numpy-side batching with a
background prefetch thread that overlaps host work with device steps
(double-buffered device put — the PrefetcherIter analog). RecordIO
parsing lives in recordio.py (+ C++ fast path in src/cc when built).
"""
from __future__ import annotations

import collections
import threading
from collections import namedtuple

import numpy as np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..ndarray import NDArray, array

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "CSVIter", "LibSVMIter", "MNISTIter",
           "ImageRecordIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Name+shape(+dtype/layout) descriptor (python/mxnet/io DataDesc)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return f"DataDesc[{self.name},{self.shape},{self.dtype},{self.layout}]"

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")


class DataBatch:
    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            raise TypeError(f"Data must be list of NDArrays, got {type(data)}")
        if label is not None and not isinstance(label, (list, tuple)):
            raise TypeError(f"Label must be list of NDArrays, got {type(label)}")
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return f"{type(self).__name__}: data shapes: {data_shapes} label shapes: {label_shapes}"


class DataIter:
    """Base iterator (python/mxnet/io DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


def _init_data(data, allow_empty, default_name):
    """Normalize input to list of (name, numpy) — reference io.py _init_data."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = collections.OrderedDict([(default_name, data[0])])
        else:
            data = collections.OrderedDict(
                [(f"_{i}_{default_name}", d) for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them or dict")
    out = collections.OrderedDict()
    for k, v in data.items():
        out[k] = v.asnumpy() if isinstance(v, NDArray) else np.asarray(v)
    return list(out.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays with optional shuffle and padding
    (python/mxnet/io NDArrayIter, incl. pad/discard/roll_over)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", ctx=None):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self.ctx = ctx or current_context()
        self.num_data = self.idx.shape[0]
        if last_batch_handle == "discard":
            self.num_data = (self.num_data // batch_size) * batch_size
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size"
        self.cursor = -batch_size
        self._cache_data = None
        self._cache_label = None
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        if self.last_batch_handle == "roll_over" and \
                0 < self.cursor < self.num_data:
            self.cursor = self.cursor - self.num_data - self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _getdata(self, data_source):
        start = max(self.cursor, 0)
        end = min(self.cursor + self.batch_size, self.num_data)
        sel = self.idx[start:end]
        out = []
        for _, arr in data_source:
            part = arr[sel]
            if part.shape[0] < self.batch_size and self.last_batch_handle == "pad":
                pad_n = self.batch_size - part.shape[0]
                wrap = arr[self.idx[:pad_n]]
                part = np.concatenate([part, wrap], axis=0)
            out.append(array(part, ctx=self.ctx))
        return out

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        start = max(self.cursor, 0)
        end = min(self.cursor + self.batch_size, self.num_data)
        return self.idx[start:end]


class ResizeIter(DataIter):
    """Resize another iterator to `size` batches per epoch."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch (src/io/prefetcher.h analog): overlaps
    host-side batch assembly + H2D with device compute."""

    def __init__(self, iters, rename_data=None, rename_label=None, depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self._queue: collections.deque = collections.deque()
        self._depth = depth
        self._lock = threading.Lock()
        self._stop = False
        self._exhausted = False
        self._cv = threading.Condition(self._lock)
        self._thread = threading.Thread(target=self._worker,
                                        name="mxnet_tpu_io_prefetch",
                                        daemon=True)
        self._thread.start()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(r, dict) else x
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def _worker(self):
        while True:
            with self._cv:
                while len(self._queue) >= self._depth and not self._stop:
                    self._cv.wait()
                if self._stop:
                    return
            try:
                batches = [i.next() for i in self.iters]
                item = DataBatch(
                    data=sum([b.data for b in batches], []),
                    label=sum([(b.label or []) for b in batches], []),
                    pad=batches[0].pad, index=batches[0].index)
            except StopIteration:
                item = None
            with self._cv:
                self._queue.append(item)
                self._cv.notify_all()
                if item is None:
                    while not self._stop and len(self._queue) > 0 and self._queue[-1] is None:
                        self._cv.wait()
                    if self._stop:
                        return

    def reset(self):
        with self._cv:
            self._queue.clear()
        for i in self.iters:
            i.reset()
        with self._cv:
            self._cv.notify_all()

    def next(self):
        with self._cv:
            while not self._queue:
                self._cv.wait()
            item = self._queue.popleft()
            self._cv.notify_all()
        if item is None:
            raise StopIteration
        return item

    def iter_next(self):
        try:
            self._peek = self.next()
            return True
        except StopIteration:
            return False

    def __del__(self):
        with self._cv:
            self._stop = True
            self._cv.notify_all()


class CSVIter(DataIter):
    """CSV file iterator (src/io/iter_csv.cc analog)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32", ctx=None, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=dtype)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=dtype)
            label = label.reshape((-1,) + tuple(label_shape))
        self._inner = NDArrayIter(data, label, batch_size,
                                  last_batch_handle="roll_over" if round_batch else "pad",
                                  ctx=ctx)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


def _parse_libsvm(path, num_features):
    """Parse LibSVM text into CSR triple + labels.

    Format per line: ``<label...> <idx>:<val> <idx>:<val> ...`` with
    zero-based feature indices (the reference LibSVMIter contract,
    src/io/iter_libsvm.cc — NOT the 1-based convention of libsvm
    itself). Multiple leading bare numbers form a multi-value label.
    Returns (data, indices, indptr, labels) numpy arrays; labels has
    shape (n,) when every line has one label else (n, label_width).
    """
    data, indices, indptr, labels = [], [], [0], []
    label_width = None
    with open(path) as f:
        for ln, line in enumerate(f):
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            toks = line.split()
            lab = []
            k = 0
            for t in toks:
                if ":" in t:
                    break
                lab.append(float(t))
                k += 1
            if label_width is None:
                label_width = len(lab)
            elif label_width != len(lab):
                raise MXNetError(
                    f"{path}:{ln + 1}: inconsistent label width "
                    f"({len(lab)} vs {label_width})")
            labels.append(lab)
            for t in toks[k:]:
                i, _, v = t.partition(":")
                i = int(i)
                if not 0 <= i < num_features:
                    raise MXNetError(
                        f"{path}:{ln + 1}: feature index {i} outside "
                        f"data_shape ({num_features}); indices are "
                        "ZERO-based (reference LibSVMIter contract)")
                indices.append(i)
                data.append(float(v))
            indptr.append(len(indices))
    labels = np.asarray(labels, np.float32)
    if label_width == 1:
        labels = labels[:, 0]
    return (np.asarray(data, np.float32), np.asarray(indices, np.int64),
            np.asarray(indptr, np.int64), labels)


class LibSVMIter(DataIter):
    """LibSVM text → CSR batch iterator (src/io/iter_libsvm.cc analog;
    the input path of the reference's sparse linear-classification
    examples, example/sparse/linear_classification).

    Yields ``DataBatch`` whose data is a :class:`CSRNDArray`; the label
    comes inline from the data file, or from ``label_libsvm`` (also
    LibSVM-format, for multi-dimensional labels). Whole-file parse at
    construction (the reference streams chunks; these files are
    host-RAM-sized here), per-batch CSR slicing after.

    TPU note: downstream compute wants static shapes — ``max_row_nnz``
    (the densest row of the file) is exposed so callers can convert
    batches to fixed-width padded gather form with
    ``mxnet_tpu.ndarray.sparse.csr_to_ell`` (see example/
    sparse_linear.py); nnz varies per batch in raw CSR form.
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True,
                 num_parts=1, part_index=0, ctx=None, **kwargs):
        super().__init__(batch_size)
        if isinstance(data_shape, int):
            data_shape = (data_shape,)
        if len(data_shape) != 1:
            raise MXNetError("LibSVMIter: data_shape must be "
                             "(num_features,)")
        self._nfeat = int(data_shape[0])
        self.ctx = ctx or current_context()
        d, i, p, lab = _parse_libsvm(data_libsvm, self._nfeat)
        if label_libsvm is not None:
            if isinstance(label_shape, int):
                label_shape = (label_shape,)
            lw = int(label_shape[0]) if label_shape else 1
            ld, li, lp, _ = _parse_libsvm(label_libsvm, lw)
            n = len(lp) - 1
            dense = np.zeros((n, lw), np.float32)
            rows = np.repeat(np.arange(n), np.diff(lp))
            dense[rows, li] = ld
            lab = dense[:, 0] if lw == 1 else dense
        n = len(p) - 1
        if lab.shape[0] != n:
            raise MXNetError(
                f"LibSVMIter: {n} data rows vs {lab.shape[0]} labels")
        # worker sharding (num_parts/part_index — reference dmlc
        # InputSplit role): contiguous row ranges
        lo = n * part_index // num_parts
        hi = n * (part_index + 1) // num_parts
        self._indptr = p[lo:hi + 1] - p[lo]
        self._indices = i[p[lo]:p[hi]]
        self._values = d[p[lo]:p[hi]]
        self._labels = lab[lo:hi]
        self.num_data = hi - lo
        if self.num_data < batch_size:
            raise MXNetError("batch_size larger than the (sharded) data")
        self.round_batch = round_batch
        self.max_row_nnz = int(np.diff(self._indptr).max()) \
            if self.num_data else 0
        self.cursor = -batch_size

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self._nfeat))]

    @property
    def provide_label(self):
        shp = (self.batch_size,) + self._labels.shape[1:]
        return [DataDesc("softmax_label", shp)]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def _rows(self):
        """Row ids of the current batch (wraps when round_batch)."""
        sel = np.arange(self.cursor,
                        min(self.cursor + self.batch_size, self.num_data))
        short = self.batch_size - sel.shape[0]
        if short > 0 and self.round_batch:
            sel = np.concatenate([sel, np.arange(short)])
        return sel

    def getdata(self):
        from ..ndarray.sparse import csr_matrix
        sel = self._rows()
        lens = np.diff(self._indptr)[sel]
        starts = self._indptr[sel]
        pos = np.concatenate([np.arange(s, s + l)
                              for s, l in zip(starts, lens)]) \
            if sel.shape[0] else np.empty(0, np.int64)
        indptr = np.concatenate([[0], np.cumsum(lens)])
        return [csr_matrix((self._values[pos], self._indices[pos], indptr),
                           shape=(sel.shape[0], self._nfeat), ctx=self.ctx)]

    def getlabel(self):
        return [array(self._labels[self._rows()], ctx=self.ctx)]

    def getpad(self):
        end = self.cursor + self.batch_size
        if self.round_batch and end > self.num_data:
            return end - self.num_data
        return 0

    def getindex(self):
        return self._rows()


class MNISTIter(DataIter):
    """MNIST idx-format iterator (src/io/iter_mnist.cc analog)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 seed=0, silent=False, num_parts=1, part_index=0, ctx=None, **kwargs):
        super().__init__(batch_size)
        import gzip
        import struct

        def read_idx(path):
            opener = gzip.open if path.endswith(".gz") else open
            with opener(path, "rb") as f:
                _, _, dims = struct.unpack(">HBB", f.read(4))
                shape = tuple(struct.unpack(">I", f.read(4))[0] for _ in range(dims))
                return np.frombuffer(f.read(), dtype=np.uint8).reshape(shape)

        img = read_idx(image).astype(np.float32) / 255.0
        lbl = read_idx(label).astype(np.float32)
        if num_parts > 1:
            img = img[part_index::num_parts]
            lbl = lbl[part_index::num_parts]
        if not flat:
            img = img.reshape(-1, 1, 28, 28)
        else:
            img = img.reshape(-1, 784)
        self._inner = NDArrayIter(img, lbl, batch_size, shuffle=shuffle, ctx=ctx)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def iter_next(self):
        return self._inner.iter_next()


def ImageRecordIter(**kwargs):
    """RecordIO image pipeline (iter_image_recordio_2.cc analog).

    Prefers the NATIVE C++ pipeline (src/cc/image_batcher.cc: threaded
    libjpeg decode + resize + CHW batch assembly, no GIL) when the
    request fits it — plain resize-to-data_shape with no python
    augmenter chain; otherwise (or when the native lib can't build)
    falls back to the python ImageRecordIterPy."""
    aug_keys = {"rand_crop", "rand_mirror", "mean_r", "mean_g", "mean_b",
                "std_r", "std_g", "std_b", "rand_gray", "brightness",
                "contrast", "saturation", "aug_list", "resize", "mean",
                "std"}
    wants_aug = any(kwargs.get(k) for k in aug_keys) \
        or int(kwargs.get("label_width", 1) or 1) > 1
    if not wants_aug and kwargs.get("path_imgidx"):
        try:
            return ImageRecordIterNative(**kwargs)
        except Exception:
            pass
    from ..image import ImageRecordIterPy
    return ImageRecordIterPy(**kwargs)


class ImageRecordIterNative(DataIter):
    """DataIter over the native C++ image batcher."""

    def __init__(self, path_imgrec=None, path_imgidx=None, data_shape=(3, 224, 224),
                 batch_size=32, shuffle=False, seed=0,
                 preprocess_threads=4, num_parts=1, part_index=0,
                 label_width=1, data_name="data", label_name="softmax_label",
                 ctx=None, dtype="float32", **kwargs):
        from . import native
        from ..context import current_context
        super().__init__(batch_size)
        self._ctx = ctx or current_context()
        self._dtype = dtype
        self._shape = tuple(data_shape)
        self._batcher = native.NativeImageBatcher(
            path_imgrec, path_imgidx, batch_size=batch_size,
            data_shape=self._shape, num_threads=preprocess_threads,
            shuffle=shuffle, seed=seed, num_parts=num_parts,
            part_index=part_index)
        self._data_name = data_name
        self._label_name = label_name

    @property
    def provide_data(self):
        return [DataDesc(self._data_name, (self.batch_size,) + self._shape)]

    @property
    def provide_label(self):
        return [DataDesc(self._label_name, (self.batch_size,))]

    def reset(self):
        self._batcher.reset()

    def next(self):
        out = self._batcher.next()
        if out is None:
            raise StopIteration
        from .. import ndarray as nd
        data, labels = out
        # raw 0-255 pixel values, matching the python ImageRecordIterPy
        # path (the reference also leaves scaling to mean/std augmenters)
        x = nd.array(data, ctx=self._ctx).astype(self._dtype)
        y = nd.array(labels, ctx=self._ctx)
        return DataBatch(data=[x], label=[y], pad=0)
