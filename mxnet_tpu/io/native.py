"""ctypes binding to the native IO library (src/cc/recordio.cc).

The reference's IO hot path is C++ (dmlc recordio + threaded iter);
this binds the TPU-native equivalent. The library is built on first use
with the repo Makefile (g++ is in the image; no pybind11 — plain C ABI
via ctypes, per the environment constraints).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_LOCK = threading.Lock()
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "src", "cc")
_LIB_PATH = os.path.join(_SRC_DIR, "libmxtpu_io.so")


class NativeIOUnavailable(RuntimeError):
    pass


def _load():
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if not os.path.exists(_LIB_PATH) or \
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(
                    os.path.join(_SRC_DIR, "recordio.cc")):
            try:
                subprocess.run(["make", "-C", _SRC_DIR], check=True,
                               capture_output=True)
            except (subprocess.CalledProcessError, FileNotFoundError) as e:
                raise NativeIOUnavailable(
                    f"could not build native IO library: {e}") from e
        lib = ctypes.CDLL(_LIB_PATH)
        lib.mxio_reader_open.restype = ctypes.c_void_p
        lib.mxio_reader_open.argtypes = [ctypes.c_char_p]
        lib.mxio_reader_next.restype = ctypes.c_int64
        lib.mxio_reader_next.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_char_p)]
        lib.mxio_reader_close.argtypes = [ctypes.c_void_p]
        lib.mxio_batcher_create.restype = ctypes.c_void_p
        lib.mxio_batcher_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64]
        lib.mxio_batcher_num_batches.restype = ctypes.c_int64
        lib.mxio_batcher_num_batches.argtypes = [ctypes.c_void_p]
        lib.mxio_batcher_next.restype = ctypes.c_int64
        lib.mxio_batcher_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
        lib.mxio_batcher_free_batch.argtypes = [ctypes.c_void_p]
        lib.mxio_batcher_reset.argtypes = [ctypes.c_void_p]
        lib.mxio_batcher_close.argtypes = [ctypes.c_void_p]
        _LIB = lib
        return lib


def available() -> bool:
    try:
        _load()
        return True
    except NativeIOUnavailable:
        return False


class NativeRecordReader:
    """Sequential reader over a RecordIO file (native framing)."""

    def __init__(self, path):
        self._lib = _load()
        self._h = self._lib.mxio_reader_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def read(self):
        buf = ctypes.c_char_p()
        n = self._lib.mxio_reader_next(self._h, ctypes.byref(buf))
        if n < 0:
            return None
        return ctypes.string_at(buf, n)

    def close(self):
        if self._h:
            self._lib.mxio_reader_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


class NativeBatcher:
    """Threaded prefetching record batcher (iter_image_recordio_2 analog)."""

    def __init__(self, rec_path, idx_path=None, batch_size=32, num_threads=4,
                 shuffle=False, seed=0, num_parts=1, part_index=0):
        self._lib = _load()
        self._h = self._lib.mxio_batcher_create(
            rec_path.encode(), (idx_path or "").encode(), batch_size,
            num_threads, int(shuffle), seed, num_parts, part_index)
        if not self._h:
            raise IOError(f"cannot open {rec_path}")

    @property
    def num_batches(self):
        return self._lib.mxio_batcher_num_batches(self._h)

    def next(self):
        """Returns list[bytes] for one batch, or None at epoch end."""
        batch = ctypes.c_void_p()
        data = ctypes.c_char_p()
        offsets = ctypes.POINTER(ctypes.c_int64)()
        n = self._lib.mxio_batcher_next(self._h, ctypes.byref(batch),
                                        ctypes.byref(data),
                                        ctypes.byref(offsets))
        if n == 0:
            return None
        records = []
        base = ctypes.cast(data, ctypes.c_void_p).value
        for i in range(n):
            lo, hi = offsets[i], offsets[i + 1]
            records.append(ctypes.string_at(base + lo, hi - lo))
        self._lib.mxio_batcher_free_batch(batch)
        return records

    def reset(self):
        self._lib.mxio_batcher_reset(self._h)

    def close(self):
        if self._h:
            self._lib.mxio_batcher_close(self._h)
            self._h = None

    def __del__(self):
        self.close()
