"""ctypes binding to the native IO library (src/cc/recordio.cc).

The reference's IO hot path is C++ (dmlc recordio + threaded iter);
this binds the TPU-native equivalent. The library is built on first use
with the repo Makefile (g++ is in the image; no pybind11 — plain C ABI
via ctypes, per the environment constraints).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB = None
_LOCK = threading.Lock()
_SRC_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "src", "cc")
_LIB_PATH = os.path.join(_SRC_DIR, "libmxtpu_io.so")


class NativeIOUnavailable(RuntimeError):
    pass


def _load():
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        srcs = [os.path.join(_SRC_DIR, f)
                for f in ("recordio.cc", "image_batcher.cc")]
        if not os.path.exists(_LIB_PATH) or \
                os.path.getmtime(_LIB_PATH) < max(
                    os.path.getmtime(s) for s in srcs if os.path.exists(s)):
            try:
                subprocess.run(["make", "-C", _SRC_DIR], check=True,
                               capture_output=True)
            except (subprocess.CalledProcessError, FileNotFoundError) as e:
                raise NativeIOUnavailable(
                    f"could not build native IO library: {e}") from e
        lib = ctypes.CDLL(_LIB_PATH)
        lib.mxio_reader_open.restype = ctypes.c_void_p
        lib.mxio_reader_open.argtypes = [ctypes.c_char_p]
        lib.mxio_reader_next.restype = ctypes.c_int64
        lib.mxio_reader_next.argtypes = [ctypes.c_void_p,
                                         ctypes.POINTER(ctypes.c_char_p)]
        lib.mxio_reader_close.argtypes = [ctypes.c_void_p]
        lib.mxio_batcher_create.restype = ctypes.c_void_p
        lib.mxio_batcher_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64]
        lib.mxio_batcher_num_batches.restype = ctypes.c_int64
        lib.mxio_batcher_num_batches.argtypes = [ctypes.c_void_p]
        lib.mxio_batcher_next.restype = ctypes.c_int64
        lib.mxio_batcher_next.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64))]
        lib.mxio_batcher_free_batch.argtypes = [ctypes.c_void_p]
        lib.mxio_batcher_reset.argtypes = [ctypes.c_void_p]
        lib.mxio_batcher_close.argtypes = [ctypes.c_void_p]
        # image pipeline (decode+resize+batch on C++ threads)
        lib.mximg_batcher_create.restype = ctypes.c_void_p
        lib.mximg_batcher_create.argtypes = [
            ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int,
            ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint64,
            ctypes.c_int64, ctypes.c_int64]
        lib.mximg_batcher_num_batches.restype = ctypes.c_int64
        lib.mximg_batcher_num_batches.argtypes = [ctypes.c_void_p]
        lib.mximg_batcher_next.restype = ctypes.c_int64
        lib.mximg_batcher_next.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.mximg_batcher_reset.argtypes = [ctypes.c_void_p]
        lib.mximg_batcher_close.argtypes = [ctypes.c_void_p]
        lib.mximg_decode.restype = ctypes.c_int
        lib.mximg_decode.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                     ctypes.c_int, ctypes.c_int,
                                     ctypes.c_void_p]
        _LIB = lib
        return lib


def available() -> bool:
    try:
        _load()
        return True
    except NativeIOUnavailable:
        return False


class NativeRecordReader:
    """Sequential reader over a RecordIO file (native framing)."""

    def __init__(self, path):
        self._lib = _load()
        self._h = self._lib.mxio_reader_open(path.encode())
        if not self._h:
            raise IOError(f"cannot open {path}")

    def read(self):
        buf = ctypes.c_char_p()
        n = self._lib.mxio_reader_next(self._h, ctypes.byref(buf))
        if n < 0:
            return None
        return ctypes.string_at(buf, n)

    def close(self):
        if self._h:
            self._lib.mxio_reader_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


class NativeBatcher:
    """Threaded prefetching record batcher (iter_image_recordio_2 analog)."""

    def __init__(self, rec_path, idx_path=None, batch_size=32, num_threads=4,
                 shuffle=False, seed=0, num_parts=1, part_index=0):
        self._lib = _load()
        self._h = self._lib.mxio_batcher_create(
            rec_path.encode(), (idx_path or "").encode(), batch_size,
            num_threads, int(shuffle), seed, num_parts, part_index)
        if not self._h:
            raise IOError(f"cannot open {rec_path}")

    @property
    def num_batches(self):
        return self._lib.mxio_batcher_num_batches(self._h)

    def next(self):
        """Returns list[bytes] for one batch, or None at epoch end."""
        batch = ctypes.c_void_p()
        data = ctypes.c_char_p()
        offsets = ctypes.POINTER(ctypes.c_int64)()
        n = self._lib.mxio_batcher_next(self._h, ctypes.byref(batch),
                                        ctypes.byref(data),
                                        ctypes.byref(offsets))
        if n == 0:
            return None
        records = []
        base = ctypes.cast(data, ctypes.c_void_p).value
        for i in range(n):
            lo, hi = offsets[i], offsets[i + 1]
            records.append(ctypes.string_at(base + lo, hi - lo))
        self._lib.mxio_batcher_free_batch(batch)
        return records

    def reset(self):
        self._lib.mxio_batcher_reset(self._h)

    def close(self):
        if self._h:
            self._lib.mxio_batcher_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


class NativeImageBatcher:
    """Full native image pipeline (src/cc/image_batcher.cc — the
    iter_image_recordio_2.cc equivalent): RecordIO framing, IRHeader
    parse, libjpeg decode, bilinear resize and CHW batch assembly on
    C++ threads. Each next() fills caller-owned numpy buffers — one
    contiguous uint8 (B,3,H,W) batch + float32 labels, ready for a
    single device_put. Partial final batches are discarded
    (last_batch='discard')."""

    def __init__(self, rec_path, idx_path, batch_size=32, data_shape=(3, 224, 224),
                 num_threads=4, shuffle=False, seed=0, num_parts=1,
                 part_index=0):
        import numpy as np
        self._np = np
        self._lib = _load()
        c, h, w = data_shape
        assert c == 3, "native image pipeline decodes RGB (3 channels)"
        self._shape = (batch_size, c, h, w)
        self._h = self._lib.mximg_batcher_create(
            rec_path.encode(), idx_path.encode(), batch_size, h, w,
            num_threads, int(shuffle), seed, num_parts, part_index)
        if not self._h:
            raise IOError(f"cannot open {rec_path} (or fewer records than "
                          "one batch)")

    @property
    def num_batches(self):
        return self._lib.mximg_batcher_num_batches(self._h)

    def next(self):
        """(data uint8 (n,3,H,W), labels float32 (n,)) or None at epoch
        end. n < batch_size when corrupt records were skipped (the
        native layer compacts the batch)."""
        np = self._np
        data = np.empty(self._shape, np.uint8)
        labels = np.empty(self._shape[0], np.float32)
        n = self._lib.mximg_batcher_next(
            self._h, data.ctypes.data_as(ctypes.c_void_p),
            labels.ctypes.data_as(ctypes.c_void_p))
        if n < 0:
            return None
        if n < self._shape[0]:
            import warnings
            warnings.warn(f"native image batcher: {self._shape[0] - n} "
                          "corrupt record(s) skipped in batch")
            return data[:n], labels[:n]
        return data, labels

    def reset(self):
        self._lib.mximg_batcher_reset(self._h)

    def close(self):
        if self._h:
            self._lib.mximg_batcher_close(self._h)
            self._h = None

    def __del__(self):
        self.close()


def decode_jpeg(buf, out_h, out_w):
    """Native single-image decode+resize → uint8 (3, out_h, out_w)."""
    import numpy as np
    lib = _load()
    out = np.empty((3, out_h, out_w), np.uint8)
    rc = lib.mximg_decode(buf, len(buf), out_h, out_w,
                          out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        raise ValueError("corrupt JPEG")
    return out
