"""Sequence packing for variable-length token batches.

The padded BERT leg burns ~26% of every step attending over and
backpropagating through padding (BENCH_r05: valid_frac 0.74 at seq512).
Packing recovers it: multiple variable-length sequences share one fixed
(batch, seq_len) row, and the flash-attention kernel's ``segment_ids``
path (ops/pallas/flash_attention.py) keeps attention block-diagonal so
sequences never see each other — the T5/MaxText-style TPU fix, and the
TPU-native continuation of the reference's bucketing heritage
(BucketingModule binned lengths into a few compiled shapes; packing
bins them into ONE shape with near-zero waste).

Layout contract (shared with the kernel and the gluon/bench consumers):

- ``data``        (R, L): tokens, first-fit-packed, padded with
                  ``pad_value``;
- ``segment_ids`` (R, L) int32: 1..n per row in placement order, 0 on
                  padding — contiguous, monotonically non-decreasing
                  within a row (what makes the kernel's min/max
                  block-skip tight);
- ``positions``   (R, L) int32: PER-SEGMENT 0-based positions (each
                  sequence's positional embedding restarts at 0), 0 on
                  padding;
- ``valid_length``(R,) int32: used slots per row (segments are packed
                  from position 0, so this is also the kv length the
                  kernel masks with).

Loss masks derive as ``segment_ids > 0``.

Positions are bounded by each SAMPLE's length, not the row length —
so a model with a finite position table (BERT ``max_length``) can pack
into rows LONGER than the table as long as every individual sample
stays within it (the bench packs 512-max samples into 2048-slot rows
against a 512-entry table).

``pack_sequences`` is greedy first-fit in arrival order — the online
algorithm a streaming corpus reader can run (rows stay open until the
stream ends). For a bench-style fixed row budget, pack a modest
oversample and keep the fullest rows (bench.py does this; first-fit's
open tail rows are the only low-occupancy ones).
"""
from __future__ import annotations

from collections import namedtuple

import numpy as np

__all__ = ["PackedBatch", "Placement", "pack_sequences", "unpack_sequences",
           "packing_efficiency", "PackedBatchify", "PackedSeqIter",
           "StreamingPacker", "stream_pack"]


PackedBatch = namedtuple(
    "PackedBatch",
    ["data", "segment_ids", "positions", "valid_length", "placements",
     "extras"])

# where sample i landed: data[row, offset:offset+length] (segment_ids
# there are == segment; kept per-sample so unpack is exact)
Placement = namedtuple("Placement", ["row", "offset", "length", "segment"])


def pack_sequences(sequences, seq_len, extras=None, pad_value=0,
                   dtype=None, max_rows=None):
    """Greedy first-fit packing of 1-D samples into (R, seq_len) rows.

    Parameters
    ----------
    sequences : list of 1-D arrays (the token samples), each with
        0 < len <= seq_len.
    extras : optional list of lists of 1-D arrays, each parallel to
        ``sequences`` (labels, weights, ...) and length-equal per
        sample; packed into identical layouts.
    max_rows : refuse placements that would open row max_rows+1 —
        samples that no open row can hold raise (the bench packs with
        an unbounded row count and selects rows afterwards).

    Returns a :class:`PackedBatch`; ``extras`` in the result is a list
    of (R, seq_len) arrays parallel to the input extras.
    """
    seqs = [np.asarray(s).reshape(-1) for s in sequences]
    extras = [list(map(np.asarray, ex)) for ex in (extras or [])]
    for ex in extras:
        if len(ex) != len(seqs):
            raise ValueError("extras must parallel sequences")
    if dtype is None:
        dtype = seqs[0].dtype if seqs else np.int32

    used = []          # per open row: slots consumed
    counts = []        # per open row: number of segments placed
    placements = []
    for idx, s in enumerate(seqs):
        n = len(s)
        if not 0 < n <= seq_len:
            raise ValueError(
                f"sample {idx} has length {n}, outside (0, {seq_len}]")
        if extras:
            for ex in extras:
                if len(ex[idx]) != n:
                    raise ValueError(
                        f"extra for sample {idx} has length "
                        f"{len(ex[idx])} != {n}")
        for r in range(len(used)):      # first fit
            if used[r] + n <= seq_len:
                break
        else:
            r = len(used)
            if max_rows is not None and r >= max_rows:
                raise ValueError(
                    f"sample {idx} (len {n}) does not fit in any of the "
                    f"{max_rows} allowed rows")
            used.append(0)
            counts.append(0)
        placements.append(Placement(r, used[r], n, counts[r] + 1))
        used[r] += n
        counts[r] += 1

    rows = len(used)
    data = np.full((rows, seq_len), pad_value, dtype=dtype)
    seg = np.zeros((rows, seq_len), np.int32)
    pos = np.zeros((rows, seq_len), np.int32)
    packed_extras = [
        np.zeros((rows, seq_len), ex[0].dtype if ex else np.int32)
        for ex in extras]
    for s, pl, i in zip(seqs, placements, range(len(seqs))):
        sl = slice(pl.offset, pl.offset + pl.length)
        data[pl.row, sl] = s
        seg[pl.row, sl] = pl.segment
        pos[pl.row, sl] = np.arange(pl.length)
        for ex, out in zip(extras, packed_extras):
            out[pl.row, sl] = ex[i]
    valid = np.asarray(used, np.int32)
    return PackedBatch(data, seg, pos, valid, placements, packed_extras)


def unpack_sequences(packed, placements=None):
    """Restore the original sample list from a packed array.

    ``packed`` is a PackedBatch (its own placements are used) or a bare
    (R, L[, ...]) array with ``placements`` given — the latter unpacks
    any array sharing the packed layout (model outputs: per-token
    logits/hidden states slice the same way)."""
    if placements is None:
        placements = packed.placements
        packed = packed.data
    return [np.asarray(packed)[p.row, p.offset:p.offset + p.length]
            for p in placements]


def packing_efficiency(batch):
    """Fraction of slots holding real tokens (PackedBatch or a
    segment_ids array)."""
    seg = batch.segment_ids if isinstance(batch, PackedBatch) else batch
    seg = np.asarray(seg)
    return float((seg > 0).sum()) / seg.size


class PackedBatchify:
    """``DataLoader(..., batchify_fn=PackedBatchify(seq_len))``: pack
    the sampled variable-length sequences into fixed rows.

    Samples are 1-D token arrays, or (tokens, label_arrays...) tuples
    with per-token labels packed into the same layout. Returns
    ``(data, segment_ids, positions, valid_length[, labels...])`` as
    numpy — worker-process safe (never touches device arrays; the
    parent wraps, matching default_mp_batchify_fn's contract)."""

    def __init__(self, seq_len, pad_value=0):
        self._seq_len = seq_len
        self._pad = pad_value

    def __call__(self, samples):
        if isinstance(samples[0], tuple):
            cols = list(zip(*samples))
            seqs, label_cols = cols[0], cols[1:]
        else:
            seqs, label_cols = samples, ()
        batch = pack_sequences(seqs, self._seq_len,
                               extras=[list(c) for c in label_cols],
                               pad_value=self._pad)
        return (batch.data, batch.segment_ids, batch.positions,
                batch.valid_length, *batch.extras)


class PackedSeqIter:
    """DataIter over packed rows (the Module-path consumer).

    Packs the whole sample list up front (first-fit, arrival order) and
    yields DataBatch(data=[tokens, segment_ids, positions, valid_length],
    label=[packed labels...]) of ``batch_size`` rows. The final partial
    row-batch pads with empty rows and reports ``pad`` (NDArrayIter's
    last-batch convention).
    """

    def __init__(self, sequences, seq_len, batch_size, labels=None,
                 pad_value=0, data_name="data", label_name="softmax_label"):
        from . import io as _io

        self._io = _io
        batch = pack_sequences(
            sequences, seq_len,
            extras=[labels] if labels is not None else None,
            pad_value=pad_value)
        self.packed = batch
        self.batch_size = batch_size
        self._seq_len = seq_len
        arrays = [batch.data, batch.segment_ids, batch.positions,
                  batch.valid_length]
        self._data_names = [data_name, "segment_ids", "positions",
                            "valid_length"]
        self._arrays = arrays
        self._labels = list(batch.extras)
        self._label_names = [label_name] if self._labels else []
        self._rows = batch.data.shape[0]
        self._cursor = 0

    @property
    def provide_data(self):
        return [self._io.DataDesc(n, (self.batch_size,) + a.shape[1:],
                                  a.dtype)
                for n, a in zip(self._data_names, self._arrays)]

    @property
    def provide_label(self):
        return [self._io.DataDesc(n, (self.batch_size,) + a.shape[1:],
                                  a.dtype)
                for n, a in zip(self._label_names, self._labels)]

    def reset(self):
        self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from .. import ndarray as nd

        if self._cursor >= self._rows:
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._rows)
        self._cursor = hi
        pad = self.batch_size - (hi - lo)

        def take(a):
            out = a[lo:hi]
            if pad:
                out = np.concatenate(
                    [out, np.zeros((pad,) + a.shape[1:], a.dtype)])
            return nd.array(out, dtype=str(out.dtype))

        return self._io.DataBatch(
            data=[take(a) for a in self._arrays],
            label=[take(a) for a in self._labels],
            pad=pad)


class StreamingPacker:
    """Online first-fit packer over a BOUNDED set of open rows.

    ``pack_sequences`` needs the whole sample list up front; a corpus
    reader (or a serving batcher) sees samples one at a time and cannot
    hold an unbounded open-row set. This packer keeps at most
    ``open_rows`` rows open: a sample first-fits into an open row, and
    when none fits and the buffer is full, the FULLEST open row is
    closed and emitted — the bounded-buffer variant of the same greedy
    algorithm (what the module docstring calls "the online algorithm a
    streaming corpus reader can run", now actually runnable on an
    endless stream).

    ``add`` returns the list of rows the call closed (usually empty);
    ``flush`` closes and returns everything still open. Each emitted
    row is a 1-row :class:`PackedBatch` sharing the layout contract
    above; ``placements`` are in the order the samples were added to
    that row.
    """

    def __init__(self, seq_len, open_rows=8, pad_value=0, dtype=None):
        if open_rows < 1:
            raise ValueError("open_rows must be >= 1")
        self._seq_len = seq_len
        self._open_rows = open_rows
        self._pad = pad_value
        self._dtype = dtype
        self._open = []   # list of dicts: used, samples=[(seq, extras)]

    @property
    def open_rows(self):
        """(used_slots, n_samples) per currently-open row."""
        return [(row["used"], len(row["samples"])) for row in self._open]

    def _emit(self, row):
        seqs = [s for s, _ in row["samples"]]
        n_extras = len(row["samples"][0][1])
        extras = [[ex[e] for _, ex in row["samples"]]
                  for e in range(n_extras)] or None
        # the samples fit one row by construction, so offline first-fit
        # over just them reproduces the exact single-row layout
        return pack_sequences(seqs, self._seq_len, extras=extras,
                              pad_value=self._pad, dtype=self._dtype,
                              max_rows=1)

    def add(self, seq, extras=()):
        """Place one sample; returns the rows this call closed."""
        seq = np.asarray(seq).reshape(-1)
        n = len(seq)
        if not 0 < n <= self._seq_len:
            raise ValueError(
                f"sample has length {n}, outside (0, {self._seq_len}]")
        extras = tuple(np.asarray(e) for e in extras)
        for e in extras:
            if len(e) != n:
                raise ValueError(
                    f"extra has length {len(e)} != sample length {n}")
        if self._open and len(extras) != len(self._open[0]["samples"][0][1]):
            raise ValueError("extras arity changed mid-stream")
        closed = []
        for row in self._open:                      # first fit
            if row["used"] + n <= self._seq_len:
                row["used"] += n
                row["samples"].append((seq, extras))
                return closed
        if len(self._open) >= self._open_rows:
            # no open row fits: close the fullest (it has the least
            # headroom left — the row least likely to ever fit again)
            fullest = max(range(len(self._open)),
                          key=lambda i: self._open[i]["used"])
            closed.append(self._emit(self._open.pop(fullest)))
        self._open.append({"used": n, "samples": [(seq, extras)]})
        return closed

    def flush(self):
        """Close every open row (stream end); returns them in the
        order they were opened."""
        out = [self._emit(row) for row in self._open]
        self._open = []
        return out


def stream_pack(samples, seq_len, batch_rows=None, open_rows=8,
                pad_value=0, dtype=None):
    """Generator: first-fit-pack a sample stream on the fly.

    ``samples`` yields 1-D token arrays or (tokens, extra, ...) tuples
    (per-token labels/weights, as in :class:`PackedBatchify`). Rows are
    packed through a :class:`StreamingPacker` with a bounded
    ``open_rows`` buffer; with ``batch_rows=None`` each completed row
    is yielded as a 1-row :class:`PackedBatch`, otherwise rows are
    accumulated and yielded as (batch_rows, seq_len) batches (the final
    flush may yield a short batch). This is the epoch feeder the
    offline ``pack_sequences`` could not be: memory is bounded by
    ``open_rows + batch_rows`` rows regardless of corpus size."""
    packer = StreamingPacker(seq_len, open_rows=open_rows,
                             pad_value=pad_value, dtype=dtype)
    pending = []
    for sample in samples:
        if isinstance(sample, tuple):
            seq, extras = sample[0], tuple(sample[1:])
        else:
            seq, extras = sample, ()
        pending.extend(packer.add(seq, extras))
        yield from _drain(pending, batch_rows, done=False)
    pending.extend(packer.flush())
    yield from _drain(pending, batch_rows, done=True)


def _drain(pending, batch_rows, done):
    """Yield ready batches out of ``pending`` single-row packs."""
    if batch_rows is None:
        while pending:
            yield pending.pop(0)
        return
    while len(pending) >= batch_rows or (done and pending):
        rows = [pending.pop(0) for _ in range(min(batch_rows, len(pending)))]
        placements = []
        for r, row in enumerate(rows):
            placements.extend(Placement(r, p.offset, p.length, p.segment)
                              for p in row.placements)
        yield PackedBatch(
            np.concatenate([r.data for r in rows]),
            np.concatenate([r.segment_ids for r in rows]),
            np.concatenate([r.positions for r in rows]),
            np.concatenate([r.valid_length for r in rows]),
            placements,
            [np.concatenate([r.extras[e] for r in rows])
             for e in range(len(rows[0].extras))])
