"""Sequence packing for variable-length token batches.

The padded BERT leg burns ~26% of every step attending over and
backpropagating through padding (BENCH_r05: valid_frac 0.74 at seq512).
Packing recovers it: multiple variable-length sequences share one fixed
(batch, seq_len) row, and the flash-attention kernel's ``segment_ids``
path (ops/pallas/flash_attention.py) keeps attention block-diagonal so
sequences never see each other — the T5/MaxText-style TPU fix, and the
TPU-native continuation of the reference's bucketing heritage
(BucketingModule binned lengths into a few compiled shapes; packing
bins them into ONE shape with near-zero waste).

Layout contract (shared with the kernel and the gluon/bench consumers):

- ``data``        (R, L): tokens, first-fit-packed, padded with
                  ``pad_value``;
- ``segment_ids`` (R, L) int32: 1..n per row in placement order, 0 on
                  padding — contiguous, monotonically non-decreasing
                  within a row (what makes the kernel's min/max
                  block-skip tight);
- ``positions``   (R, L) int32: PER-SEGMENT 0-based positions (each
                  sequence's positional embedding restarts at 0), 0 on
                  padding;
- ``valid_length``(R,) int32: used slots per row (segments are packed
                  from position 0, so this is also the kv length the
                  kernel masks with).

Loss masks derive as ``segment_ids > 0``.

Positions are bounded by each SAMPLE's length, not the row length —
so a model with a finite position table (BERT ``max_length``) can pack
into rows LONGER than the table as long as every individual sample
stays within it (the bench packs 512-max samples into 2048-slot rows
against a 512-entry table).

``pack_sequences`` is greedy first-fit in arrival order — the online
algorithm a streaming corpus reader can run (rows stay open until the
stream ends). For a bench-style fixed row budget, pack a modest
oversample and keep the fullest rows (bench.py does this; first-fit's
open tail rows are the only low-occupancy ones).
"""
from __future__ import annotations

from collections import namedtuple

import numpy as np

__all__ = ["PackedBatch", "Placement", "pack_sequences", "unpack_sequences",
           "packing_efficiency", "PackedBatchify", "PackedSeqIter"]


PackedBatch = namedtuple(
    "PackedBatch",
    ["data", "segment_ids", "positions", "valid_length", "placements",
     "extras"])

# where sample i landed: data[row, offset:offset+length] (segment_ids
# there are == segment; kept per-sample so unpack is exact)
Placement = namedtuple("Placement", ["row", "offset", "length", "segment"])


def pack_sequences(sequences, seq_len, extras=None, pad_value=0,
                   dtype=None, max_rows=None):
    """Greedy first-fit packing of 1-D samples into (R, seq_len) rows.

    Parameters
    ----------
    sequences : list of 1-D arrays (the token samples), each with
        0 < len <= seq_len.
    extras : optional list of lists of 1-D arrays, each parallel to
        ``sequences`` (labels, weights, ...) and length-equal per
        sample; packed into identical layouts.
    max_rows : refuse placements that would open row max_rows+1 —
        samples that no open row can hold raise (the bench packs with
        an unbounded row count and selects rows afterwards).

    Returns a :class:`PackedBatch`; ``extras`` in the result is a list
    of (R, seq_len) arrays parallel to the input extras.
    """
    seqs = [np.asarray(s).reshape(-1) for s in sequences]
    extras = [list(map(np.asarray, ex)) for ex in (extras or [])]
    for ex in extras:
        if len(ex) != len(seqs):
            raise ValueError("extras must parallel sequences")
    if dtype is None:
        dtype = seqs[0].dtype if seqs else np.int32

    used = []          # per open row: slots consumed
    counts = []        # per open row: number of segments placed
    placements = []
    for idx, s in enumerate(seqs):
        n = len(s)
        if not 0 < n <= seq_len:
            raise ValueError(
                f"sample {idx} has length {n}, outside (0, {seq_len}]")
        if extras:
            for ex in extras:
                if len(ex[idx]) != n:
                    raise ValueError(
                        f"extra for sample {idx} has length "
                        f"{len(ex[idx])} != {n}")
        for r in range(len(used)):      # first fit
            if used[r] + n <= seq_len:
                break
        else:
            r = len(used)
            if max_rows is not None and r >= max_rows:
                raise ValueError(
                    f"sample {idx} (len {n}) does not fit in any of the "
                    f"{max_rows} allowed rows")
            used.append(0)
            counts.append(0)
        placements.append(Placement(r, used[r], n, counts[r] + 1))
        used[r] += n
        counts[r] += 1

    rows = len(used)
    data = np.full((rows, seq_len), pad_value, dtype=dtype)
    seg = np.zeros((rows, seq_len), np.int32)
    pos = np.zeros((rows, seq_len), np.int32)
    packed_extras = [
        np.zeros((rows, seq_len), ex[0].dtype if ex else np.int32)
        for ex in extras]
    for s, pl, i in zip(seqs, placements, range(len(seqs))):
        sl = slice(pl.offset, pl.offset + pl.length)
        data[pl.row, sl] = s
        seg[pl.row, sl] = pl.segment
        pos[pl.row, sl] = np.arange(pl.length)
        for ex, out in zip(extras, packed_extras):
            out[pl.row, sl] = ex[i]
    valid = np.asarray(used, np.int32)
    return PackedBatch(data, seg, pos, valid, placements, packed_extras)


def unpack_sequences(packed, placements=None):
    """Restore the original sample list from a packed array.

    ``packed`` is a PackedBatch (its own placements are used) or a bare
    (R, L[, ...]) array with ``placements`` given — the latter unpacks
    any array sharing the packed layout (model outputs: per-token
    logits/hidden states slice the same way)."""
    if placements is None:
        placements = packed.placements
        packed = packed.data
    return [np.asarray(packed)[p.row, p.offset:p.offset + p.length]
            for p in placements]


def packing_efficiency(batch):
    """Fraction of slots holding real tokens (PackedBatch or a
    segment_ids array)."""
    seg = batch.segment_ids if isinstance(batch, PackedBatch) else batch
    seg = np.asarray(seg)
    return float((seg > 0).sum()) / seg.size


class PackedBatchify:
    """``DataLoader(..., batchify_fn=PackedBatchify(seq_len))``: pack
    the sampled variable-length sequences into fixed rows.

    Samples are 1-D token arrays, or (tokens, label_arrays...) tuples
    with per-token labels packed into the same layout. Returns
    ``(data, segment_ids, positions, valid_length[, labels...])`` as
    numpy — worker-process safe (never touches device arrays; the
    parent wraps, matching default_mp_batchify_fn's contract)."""

    def __init__(self, seq_len, pad_value=0):
        self._seq_len = seq_len
        self._pad = pad_value

    def __call__(self, samples):
        if isinstance(samples[0], tuple):
            cols = list(zip(*samples))
            seqs, label_cols = cols[0], cols[1:]
        else:
            seqs, label_cols = samples, ()
        batch = pack_sequences(seqs, self._seq_len,
                               extras=[list(c) for c in label_cols],
                               pad_value=self._pad)
        return (batch.data, batch.segment_ids, batch.positions,
                batch.valid_length, *batch.extras)


class PackedSeqIter:
    """DataIter over packed rows (the Module-path consumer).

    Packs the whole sample list up front (first-fit, arrival order) and
    yields DataBatch(data=[tokens, segment_ids, positions, valid_length],
    label=[packed labels...]) of ``batch_size`` rows. The final partial
    row-batch pads with empty rows and reports ``pad`` (NDArrayIter's
    last-batch convention).
    """

    def __init__(self, sequences, seq_len, batch_size, labels=None,
                 pad_value=0, data_name="data", label_name="softmax_label"):
        from . import io as _io

        self._io = _io
        batch = pack_sequences(
            sequences, seq_len,
            extras=[labels] if labels is not None else None,
            pad_value=pad_value)
        self.packed = batch
        self.batch_size = batch_size
        self._seq_len = seq_len
        arrays = [batch.data, batch.segment_ids, batch.positions,
                  batch.valid_length]
        self._data_names = [data_name, "segment_ids", "positions",
                            "valid_length"]
        self._arrays = arrays
        self._labels = list(batch.extras)
        self._label_names = [label_name] if self._labels else []
        self._rows = batch.data.shape[0]
        self._cursor = 0

    @property
    def provide_data(self):
        return [self._io.DataDesc(n, (self.batch_size,) + a.shape[1:],
                                  a.dtype)
                for n, a in zip(self._data_names, self._arrays)]

    @property
    def provide_label(self):
        return [self._io.DataDesc(n, (self.batch_size,) + a.shape[1:],
                                  a.dtype)
                for n, a in zip(self._label_names, self._labels)]

    def reset(self):
        self._cursor = 0

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def next(self):
        from .. import ndarray as nd

        if self._cursor >= self._rows:
            raise StopIteration
        lo = self._cursor
        hi = min(lo + self.batch_size, self._rows)
        self._cursor = hi
        pad = self.batch_size - (hi - lo)

        def take(a):
            out = a[lo:hi]
            if pad:
                out = np.concatenate(
                    [out, np.zeros((pad,) + a.shape[1:], a.dtype)])
            return nd.array(out, dtype=str(out.dtype))

        return self._io.DataBatch(
            data=[take(a) for a in self._arrays],
            label=[take(a) for a in self._labels],
            pad=pad)
