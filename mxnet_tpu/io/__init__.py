from .io import (
    DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter, PrefetchingIter,
    CSVIter, LibSVMIter, MNISTIter, ImageRecordIter,
)
