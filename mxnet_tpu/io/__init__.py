from .io import (
    DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter, PrefetchingIter,
    CSVIter, MNISTIter, ImageRecordIter,
)
