from .io import (
    DataDesc, DataBatch, DataIter, NDArrayIter, ResizeIter, PrefetchingIter,
    CSVIter, LibSVMIter, MNISTIter, ImageRecordIter,
)
from .packing import (
    PackedBatch, PackedBatchify, PackedSeqIter, pack_sequences,
    unpack_sequences, packing_efficiency,
)
