"""Tail-latency attribution: per-request critical paths + /whyslow.

A firing latency page that says "p99 blown" is a question, not an
answer. A decode request's wall time is smeared across WFQ admission
wait, chunked-prefill interleaving, iteration-boundary scheduling
gaps, KV copy-on-write copies, page-exhaustion defer episodes, wire
transit and HA-journal acks — and until each of those is a *named
stage* with per-request numbers, every page ends in guesswork. This
module is the attribution layer the rest of the serving stack stamps
into:

- :data:`STAGES` — the one canonical stage-name registry. Every
  ``stage=`` label value and every ``stage/<name>`` span anywhere in
  the tree must come from here (mxlint's ``stage-name-registry``
  check fails the build otherwise), so engine, router, dashboards and
  the pager can never drift apart on what a stage is called.
- :func:`stamp` — the hot-path primitive: record one stage interval
  on a live request. It appends a ``(stage, t0, t1)`` monotonic tuple
  to the request's stamp list (the exact per-request record the
  breakdown is computed from), synthesizes a ``stage/<name>`` child
  span under the request's root span (the trace-tree view), and —
  when the scheduler left the request idle since its last stamp —
  backfills the hole as an explicit ``sched_gap`` interval so
  admitted-but-not-in-cohort time is attributed, not smeared.
- :func:`critical_path` / :func:`breakdown_from_stamps` — the
  extractor: an ordered, *gap-free* decomposition of a finished
  request's wall time. Overlapping child intervals are resolved
  innermost-wins (a COW copy inside a decode iteration bills to
  ``cow_copy``, the remainder of the iteration to ``decode_iter``),
  uncovered wall is reported as the explicit ``unattributed`` stage,
  and ``sum(stages) + unattributed == wall`` holds by construction.
  The result rides ``InferenceFuture.breakdown`` and the streamed
  final RESULT frame, so the router and loadgen see the same numbers
  the engine measured.
- :class:`StageBreakdown` — the fleet aggregator behind ``/whyslow``:
  per-stage latency histograms labeled ``(engine_id, stage,
  tenant_class, model)``, a windowed per-stage p99, the slowest
  RETRIEVABLE exemplar trace per stage, and a ``top`` ranking by
  share of attributed time. Routers merge engine snapshots with
  :func:`merge_whyslow`; firing latency alerts attach
  :func:`top_stages_for` to their payload and flight bundle.

``MXNET_TPU_ATTRIBUTION=0`` (or spans off) disables the subsystem:
no stamp tuples, no extra spans, no metric families, no threads —
the disabled hot path is one attribute check per call site.
"""
from __future__ import annotations

import heapq
import threading
from collections import deque

from .. import envvars
from . import spans as _spans
from .registry import REGISTRY

__all__ = ["STAGES", "SPAN_PREFIX", "enabled", "stamp",
           "stamp_interval", "critical_path", "breakdown_from_stamps",
           "StageBreakdown", "aggregator", "get_aggregator",
           "top_stages_for", "merge_whyslow", "reset", "configure"]

#: The canonical stage registry. Includes the legacy encoder-path
#: stage labels (queue/pack/compute/compile/total — the
#: ``mxnet_tpu_serving_latency_ms`` axis that predates this module)
#: so one tuple governs every ``stage=`` literal in the tree.
STAGES = (
    "wfq_wait",        # submit -> WFQ drain, stamped by the queue
    "defer",           # KV page-exhaustion defer episode (requeue wait)
    "sched_gap",       # admitted but not in the running cohort
    "prefill_chunk",   # one chunked-prefill step
    "prefill",         # dense (single-shot) prefill
    "decode_iter",     # decode-iteration residency
    "cow_copy",        # KV copy-on-write page copies
    "dispatch",        # router -> seat transit (rt minus engine wall)
    "ha_ack",          # HA-journal replication ack wait
    # legacy encoder-path latency axis (ServingStats / router)
    "queue", "pack", "compute", "compile", "total",
    # the explicit remainder every decomposition carries
    "unattributed",
)

_STAGESET = frozenset(STAGES)

#: Stage spans are named ``stage/<stage>`` in the trace tree.
SPAN_PREFIX = "stage/"

#: Legacy synthesized child spans mapped onto canonical stages, so
#: :func:`critical_path` decomposes pre-attribution encoder traces too.
_LEGACY_SPAN_STAGES = {
    "serving/queue": "queue",
    "serving/pack": "pack",
    "serving/forward": "compute",
    "serving/compile": "compile",
}

#: sched_gap holes narrower than this are left to ``unattributed``
#: rather than minted as spans — sub-100µs loop bookkeeping is not a
#: scheduling decision.
_GAP_MIN_S = 100e-6

_enabled_cache = None
_lock = threading.Lock()


def enabled():
    """True when stage stamping is on: ``MXNET_TPU_ATTRIBUTION`` AND
    span recording (stamps parent under the request root span; with
    spans off there is no tree to attribute)."""
    global _enabled_cache
    if _enabled_cache is None:
        _enabled_cache = bool(envvars.get("MXNET_TPU_ATTRIBUTION"))
    return _enabled_cache and _spans.enabled()


def configure(enabled=None):
    """Test/tool override (None = re-read the env on next check)."""
    global _enabled_cache
    _enabled_cache = enabled


# -- stamping ----------------------------------------------------------------
def stamp(req, stage, mono_start, mono_end, attrs=None, span=True):
    """Record one stage interval on a live request.

    ``req`` is any object with ``stages`` (list or None), ``span``
    (the root :class:`~.spans.Span`), ``trace_id`` and ``t_activity``
    slots — i.e. a serving :class:`~..serving.queue.Request`. No-op
    (one attribute check) when attribution is off for the request.

    Idle time since the request's previous stamp is backfilled as an
    explicit ``sched_gap`` interval first, so the decomposition stays
    gap-free without every call site reasoning about holes.
    """
    stamps = getattr(req, "stages", None)
    if stamps is None:
        return
    if stage not in _STAGESET:
        raise ValueError(f"stage {stage!r} not in attribution.STAGES")
    last = req.t_activity
    if (last is not None and stage != "sched_gap"
            and mono_start - last > _GAP_MIN_S):
        stamps.append(("sched_gap", last, mono_start))
        if span and len(stamps) <= _span_cap():
            _spans.record_span(SPAN_PREFIX + "sched_gap", req.trace_id,
                               parent_id=req.span.span_id,
                               mono_start=last, mono_end=mono_start)
    stamps.append((stage, mono_start, mono_end))
    # never rewinds: a nested stamp (cow_copy inside an iteration)
    # must not reopen already-covered wall as a phantom gap
    req.t_activity = mono_end if last is None else max(last, mono_end)
    if span and len(stamps) <= _span_cap():
        _spans.record_span(SPAN_PREFIX + stage, req.trace_id,
                           parent_id=req.span.span_id,
                           mono_start=mono_start, mono_end=mono_end,
                           attrs=attrs)


def stamp_interval(req, stage, interval, attrs=None):
    """:func:`stamp` from a ``(t0, t1)`` pair (both monotonic)."""
    stamp(req, stage, interval[0], interval[1], attrs=attrs)


def _span_cap():
    # per-request stage spans ride the same per-trace cap as everything
    # else; stop minting span dicts once the trace would drop them
    # anyway (the stamp TUPLES keep accumulating — the breakdown must
    # stay exact even for 10k-token generations)
    return envvars.get("MXNET_TPU_TRACE_MAX_SPANS")


# -- critical-path extraction ------------------------------------------------
def _decompose(intervals, w0, w1):
    """Sweep ``(stage, t0, t1)`` intervals over the wall ``[w0, w1]``
    into an ordered, gap-free decomposition. Overlaps resolve
    innermost-wins (latest start; ties: latest in list order), holes
    bill to ``unattributed``. Returns (ordered stage->seconds dict,
    unattributed seconds)."""
    clipped = []
    for i, (stage, t0, t1) in enumerate(intervals):
        t0, t1 = max(t0, w0), min(t1, w1)
        if t1 > t0:
            clipped.append((t0, t1, i, stage))
    totals = {}
    first_seen = {}
    unattributed = 0.0
    edges = sorted({w0, w1, *(c[0] for c in clipped),
                    *(c[1] for c in clipped)})
    # single O(n log n) sweep: intervals enter a max-heap keyed
    # (start, stamp order) as the edge walk reaches their start and
    # are lazily expired once it passes their end, so the slice owner
    # ("innermost wins": latest start, then latest stamped) is the
    # heap top. A decode request stamps one decode_iter per generated
    # token — a per-slice rescan is O(n^2) and froze the decode loop
    # for seconds on 10k-token generations.
    clipped.sort(key=lambda c: (c[0], c[2]))
    active = []                   # (-t0, -i, t1, stage)
    nxt = 0
    for a, b in zip(edges, edges[1:]):
        while nxt < len(clipped) and clipped[nxt][0] <= a:
            t0, t1, i, stage = clipped[nxt]
            heapq.heappush(active, (-t0, -i, t1, stage))
            nxt += 1
        while active and active[0][2] <= a:
            heapq.heappop(active)
        if not active:
            unattributed += b - a
        else:
            stage = active[0][3]
            totals[stage] = totals.get(stage, 0.0) + (b - a)
            first_seen.setdefault(stage, a)
    ordered = dict(sorted(totals.items(),
                          key=lambda kv: first_seen[kv[0]]))
    return ordered, unattributed


def _breakdown_dict(ordered_s, unattributed_s, wall_s, trace_id=None):
    wall_ms = wall_s * 1e3
    stages = [{"stage": s, "ms": round(v * 1e3, 3),
               "share": round(v / wall_s, 4) if wall_s > 0 else 0.0}
              for s, v in ordered_s.items()]
    out = {"wall_ms": round(wall_ms, 3),
           "stages": stages,
           "attributed_ms": round(sum(v for v in ordered_s.values())
                                  * 1e3, 3),
           "unattributed_ms": round(unattributed_s * 1e3, 3)}
    if trace_id is not None:
        out["trace_id"] = trace_id
    return out


def breakdown_from_stamps(stamps, t_submit, t_done, trace_id=None):
    """Stamp tuples + wall endpoints -> breakdown dict. This is what
    the engine computes at request completion and hangs on
    ``InferenceFuture.breakdown``:

    ``{"wall_ms", "stages": [{"stage", "ms", "share"}, ...],
    "attributed_ms", "unattributed_ms", "trace_id"}``

    with stages ordered by first occurrence on the timeline and
    ``attributed_ms + unattributed_ms == wall_ms`` (float rounding
    aside). ``share`` is of wall."""
    wall = t_done - t_submit
    if wall <= 0:
        return _breakdown_dict({}, 0.0, 0.0, trace_id)
    ordered, unattributed = _decompose(stamps or (), t_submit, t_done)
    return _breakdown_dict(ordered, unattributed, wall, trace_id)


def critical_path(spans, root_id=None):
    """Walk a finished request's span tree (a list of span dicts as
    stored by :class:`~.spans.SpanRecorder` / served at
    ``/traces/<id>``) into the same decomposition shape as
    :func:`breakdown_from_stamps`.

    The root is ``root_id`` if given, else the first span without a
    parent in the list (else the earliest span). Descendant spans
    named ``stage/<name>`` — plus the legacy synthesized children in
    :data:`_LEGACY_SPAN_STAGES` — become the stage intervals; all
    other spans are structure, not stages."""
    if not spans:
        return _breakdown_dict({}, 0.0, 0.0)
    by_id = {s["span_id"]: s for s in spans if s.get("span_id")}
    root = None
    if root_id is not None:
        root = by_id.get(root_id)
    if root is None:
        for s in spans:
            if not s.get("parent_id") or s["parent_id"] not in by_id:
                root = s
                break
    if root is None:
        root = min(spans, key=lambda s: s.get("ts_us", 0))

    def under_root(s):
        seen = 0
        cur = s
        while cur is not None and seen < len(spans) + 1:
            if cur.get("span_id") == root.get("span_id"):
                return True
            cur = by_id.get(cur.get("parent_id"))
            seen += 1
        return False

    intervals = []
    for s in spans:
        name = s.get("name", "")
        if name.startswith(SPAN_PREFIX):
            stage = name[len(SPAN_PREFIX):]
        else:
            stage = _LEGACY_SPAN_STAGES.get(name)
        if stage is None or s is root or not under_root(s):
            continue
        t0 = s.get("ts_us", 0) / 1e6
        intervals.append((stage, t0, t0 + s.get("dur_us", 0) / 1e6))
    w0 = root.get("ts_us", 0) / 1e6
    w1 = w0 + root.get("dur_us", 0) / 1e6
    wall = w1 - w0
    if wall <= 0:
        return _breakdown_dict({}, 0.0, 0.0, root.get("trace_id"))
    ordered, unattributed = _decompose(intervals, w0, w1)
    return _breakdown_dict(ordered, unattributed, wall,
                           root.get("trace_id"))


# -- fleet aggregation (/whyslow) --------------------------------------------
_STAGE_BUCKETS = (0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                  500.0, 1000.0, 2500.0, 10000.0)

_families_cache = None


def _families(registry=None):
    """The stage metric families, created on FIRST observation only —
    the disabled path registers nothing."""
    global _families_cache
    if _families_cache is None or registry is not None:
        reg = registry or REGISTRY
        _families_cache = (
            reg.histogram(
                "mxnet_tpu_serving_stage_latency_ms",
                "per-request attributed stage time (critical-path "
                "decomposition; unattributed is an explicit stage)",
                ("engine_id", "stage", "tenant_class", "model"),
                buckets=_STAGE_BUCKETS),
            reg.counter(
                "mxnet_tpu_serving_stage_seconds_total",
                "cumulative attributed stage seconds (share-over-time "
                "queries: rate this against its siblings)",
                ("engine_id", "stage", "tenant_class", "model")))
    return _families_cache


class _StageStat:
    """One (stage, tenant_class, model) cell: count/total plus a
    sliding window of the last N per-request ``(ms, trace)`` samples.
    p99 and the slowest retrievable exemplar are computed over the
    WINDOW on read, so both decay as an incident ages out — an
    eviction policy that keeps extremes forever would converge on
    all-time maxima and report a stale tail as current."""

    __slots__ = ("count", "total_ms", "window")

    def __init__(self, capacity):
        self.count = 0
        self.total_ms = 0.0
        self.window = deque(maxlen=max(1, int(capacity or 1)))

    def observe(self, ms, trace_id=None):
        self.count += 1
        self.total_ms += ms
        self.window.append((ms, trace_id))

    def p99(self):
        if not self.window:
            return None
        w = sorted(ms for ms, _ in self.window)
        i = max(0, int(0.99 * len(w) + 0.5) - 1)
        return w[min(i, len(w) - 1)]

    def exemplar(self):
        """``(ms, trace_id)`` of the slowest windowed sample carrying
        a retrievable trace, or ``(None, None)``."""
        best_ms, best_tr = None, None
        for ms, tr in self.window:
            if tr is not None and (best_ms is None or ms > best_ms):
                best_ms, best_tr = ms, tr
        return best_ms, best_tr


class StageBreakdown:
    """Per-owner stage aggregator: the ``/whyslow`` body builder.

    ``observe`` folds one request's breakdown dict in; ``snapshot``
    renders per-(stage, tenant_class, model) rows plus the ``top``
    ranking by share of attributed time, each top row carrying the
    stage's windowed p99 and slowest retrievable exemplar trace.
    """

    def __init__(self, owner, registry=None, window=None):
        self.owner = str(owner)
        self._registry = registry
        self._window = (window if window is not None
                        else envvars.get("MXNET_TPU_ATTRIBUTION_WINDOW"))
        self._lock = threading.Lock()
        self._stats = {}          # (stage, tenant_class, model) -> stat
        self._requests = 0

    def observe(self, breakdown, tenant_class=None, model=None,
                trace_id=None):
        """Fold one request's breakdown in. ``trace_id`` is attached
        as a stage exemplar only when the trace is actually
        retrievable at ``/traces/<id>`` (the tail-sampler kept it:
        wall >= the slow threshold)."""
        if not breakdown:
            return
        cls = str(tenant_class or "standard")
        mdl = str(model or "-")
        wall = breakdown.get("wall_ms") or 0.0
        retrievable = (trace_id is not None and _spans.enabled()
                       and wall >= _spans.RECORDER.slow_ms)
        ex = trace_id if retrievable else None
        hist, secs = _families(self._registry)
        rows = list(breakdown.get("stages") or ())
        un = breakdown.get("unattributed_ms")
        if un:
            rows.append({"stage": "unattributed", "ms": un})
        with self._lock:
            self._requests += 1
            for row in rows:
                stage, ms = row["stage"], float(row.get("ms") or 0.0)
                key = (stage, cls, mdl)
                st = self._stats.get(key)
                if st is None:
                    st = self._stats[key] = _StageStat(self._window)
                st.observe(ms, ex)
                hist.labels(engine_id=self.owner, stage=stage,
                            tenant_class=cls, model=mdl).observe(ms)
                secs.labels(engine_id=self.owner, stage=stage,
                            tenant_class=cls, model=mdl).inc(ms / 1e3)

    def snapshot(self, top=None):
        """The ``/whyslow`` body for this owner."""
        top = top if top is not None \
            else envvars.get("MXNET_TPU_ATTRIBUTION_TOP")
        with self._lock:
            rows = []
            by_stage = {}
            grand = 0.0
            for (stage, cls, mdl), st in sorted(self._stats.items()):
                grand += st.total_ms
                p99 = st.p99()
                ex_ms, ex_tr = st.exemplar()
                rows.append({"engine_id": self.owner, "stage": stage,
                             "tenant_class": cls, "model": mdl,
                             "count": st.count,
                             "total_ms": round(st.total_ms, 3),
                             "mean_ms": round(st.total_ms
                                              / max(1, st.count), 3),
                             "p99_ms": (None if p99 is None
                                        else round(p99, 3)),
                             "exemplar": ex_tr})
                agg = by_stage.setdefault(
                    stage, {"stage": stage, "count": 0, "total_ms": 0.0,
                            "p99_ms": 0.0, "exemplar": None,
                            "_ex_ms": -1.0})
                agg["count"] += st.count
                agg["total_ms"] += st.total_ms
                if p99 is not None:
                    agg["p99_ms"] = max(agg["p99_ms"], p99)
                if ex_tr is not None and ex_ms > agg["_ex_ms"]:
                    agg["_ex_ms"] = ex_ms
                    agg["exemplar"] = ex_tr
            requests = self._requests
        ranked = sorted(by_stage.values(),
                        key=lambda r: -r["total_ms"])
        out_top = []
        for r in ranked[:top]:
            out_top.append({"stage": r["stage"], "count": r["count"],
                            "total_ms": round(r["total_ms"], 3),
                            "share": round(r["total_ms"] / grand, 4)
                            if grand > 0 else 0.0,
                            "p99_ms": round(r["p99_ms"], 3),
                            "exemplar": r["exemplar"]})
        return {"owner": self.owner, "enabled": enabled(),
                "requests": requests, "stages": rows, "top": out_top}


def merge_whyslow(parts, owner="fleet"):
    """Router fleet merge: engine ``/whyslow`` bodies -> one table.
    Rows concatenate (each already carries its ``engine_id``); the
    ``top`` ranking is recomputed across the fleet by share of total
    attributed time, keeping each stage's worst p99 and slowest
    exemplar."""
    rows, owners = [], []
    requests = 0
    by_stage = {}
    grand = 0.0
    for part in parts:
        if not part:
            continue
        owners.append(part.get("owner"))
        requests += part.get("requests") or 0
        part_rows = list(part.get("stages") or ())
        rows.extend(part_rows)
        # the fleet ranking is recomputed from the FULL per-stage rows
        # — each part's own "top" table is pre-truncated to its local
        # top-N, so ranking from those would hide a stage that is #4
        # on every engine but #1 fleet-wide and overstate shares. Fall
        # back to "top" only for parts that carry no stage rows.
        for t in part_rows or part.get("top") or ():
            agg = by_stage.setdefault(
                t["stage"], {"stage": t["stage"], "count": 0,
                             "total_ms": 0.0, "p99_ms": 0.0,
                             "exemplar": None, "_ex": -1.0})
            agg["count"] += t.get("count") or 0
            agg["total_ms"] += t.get("total_ms") or 0.0
            agg["p99_ms"] = max(agg["p99_ms"], t.get("p99_ms") or 0.0)
            grand += t.get("total_ms") or 0.0
            if t.get("exemplar") and (t.get("p99_ms") or 0.0) > agg["_ex"]:
                agg["_ex"] = t.get("p99_ms") or 0.0
                agg["exemplar"] = t["exemplar"]
    top = []
    for r in sorted(by_stage.values(), key=lambda r: -r["total_ms"]):
        top.append({"stage": r["stage"], "count": r["count"],
                    "total_ms": round(r["total_ms"], 3),
                    "share": round(r["total_ms"] / grand, 4)
                    if grand > 0 else 0.0,
                    "p99_ms": round(r["p99_ms"], 3),
                    "exemplar": r["exemplar"]})
    return {"owner": owner, "fleet": True, "engines": owners,
            "requests": requests, "stages": rows, "top": top}


# -- process-wide aggregator registry ---------------------------------------
_AGGS = {}


def aggregator(owner, registry=None):
    """Get-or-create the owner's :class:`StageBreakdown` (engines and
    routers each own one, keyed by their id — the same key the alert
    daemon's evaluator carries, so a firing page finds its table)."""
    with _lock:
        agg = _AGGS.get(str(owner))
        if agg is None:
            agg = _AGGS[str(owner)] = StageBreakdown(owner,
                                                     registry=registry)
        return agg


def get_aggregator(owner):
    """Peek (no create): None when the owner never observed a stage —
    the alert daemon's lookup must not mint families on a quiet
    process."""
    with _lock:
        return _AGGS.get(str(owner))


def top_stages_for(owner, top=None):
    """Alert-payload attachment: the owner's current top-stage rows
    (``[{stage, share, p99_ms, count, exemplar}, ...]``) or None when
    attribution has nothing — a page reads "p99 blown, 78% of it is
    wfq_wait" straight off this."""
    agg = get_aggregator(owner)
    if agg is None:
        return None
    snap = agg.snapshot(top=top)
    return snap["top"] or None


def reset():
    """Test hook: drop aggregators + cached gates/families."""
    global _families_cache, _enabled_cache
    with _lock:
        _AGGS.clear()
    _families_cache = None
    _enabled_cache = None
