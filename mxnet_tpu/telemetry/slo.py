"""Declarative SLO registry: objectives, windowed SLIs, burn rates.

The fleet can be *measured* (metrics registry, spans, profiler, cost
ledger) but nothing here could *judge* it: this module turns raw
telemetry into objectives. An :class:`SloEvaluator` owns a set of
declared SLOs, samples their underlying (cumulative) metric series
into a bounded :class:`SampleStore` on every evaluation tick, and
answers the SRE-workbook questions about each objective:

- **SLI over a window** — the good/total ratio over the trailing
  window (partial coverage uses whatever history exists, so a freshly
  started process answers honestly rather than not at all);
- **burn rate** — ``(1 - SLI) / (1 - target)``: 1.0 means the error
  budget burns exactly at the sustainable rate, N means the budget
  burns N× too fast;
- **error budget remaining** — over the budget window
  (``MXNET_TPU_SLO_BUDGET_S``, clipped to uptime):
  ``1 - (1 - SLI) / (1 - target)`` — negative means the budget is
  blown.

Two objective shapes:

- **ratio** SLOs (:class:`LatencySLO`, :class:`AvailabilitySLO`) read
  good/total cumulative counters off the process registry — latency
  "good" is the histogram's cumulative count at the bucket boundary
  the threshold snaps up to (so the SLI is exact, not interpolated);
- **threshold** SLOs (:class:`CostSLO`, :class:`GaugeSLO`) compare a
  windowed value (a delta ratio, or an instantaneous gauge) against a
  bound; their "burn rate" is ``value/bound`` (or ``bound/value`` for
  lower-is-bad objectives) so the same alerting machinery applies.

Alert rules over these objectives — multi-window multi-burn-rate,
threshold, absence — live in :mod:`.alerts`; this module stays
policy-free (it computes, rules decide).

Every window is multiplied by ``MXNET_TPU_SLO_WINDOW_SCALE`` so a
drill can shrink hours to seconds with one knob.
"""
from __future__ import annotations

import bisect
import threading
import time
from collections import OrderedDict

from .. import envvars
from .registry import REGISTRY

__all__ = ["SampleStore", "SLO", "RatioSLO", "LatencySLO",
           "AvailabilitySLO", "ThresholdSLO", "CostSLO", "GaugeSLO",
           "SloEvaluator", "BURN_WINDOWS", "window_scale",
           "max_short_burn", "replay_history"]


def max_short_burn(snapshot, window="5m"):
    """The max burn rate over a ``/slo`` snapshot's RATIO objectives
    at the given window label (None when none answer) — the one
    "is this owner burning" scalar the router's routing weights and
    the autoscaler both judge; one helper keeps them judging the
    same signal by construction."""
    burn = None
    for row in ((snapshot or {}).get("objectives") or {}).values():
        if row.get("kind") != "ratio":
            continue
        b = (row.get("burn_rates") or {}).get(window)
        if b is not None and (burn is None or b > burn):
            burn = b
    return burn

#: canonical burn-rate windows (seconds, before scaling) — the SRE
#: workbook's multi-window pairs read these by label
BURN_WINDOWS = OrderedDict((("5m", 300.0), ("30m", 1800.0),
                            ("1h", 3600.0), ("6h", 21600.0)))


def window_scale():
    """The global window multiplier (``MXNET_TPU_SLO_WINDOW_SCALE``,
    floored at a microsecond so a zero knob can't divide the world)."""
    return max(1e-6, float(envvars.get("MXNET_TPU_SLO_WINDOW_SCALE")))


class SampleStore:
    """Bounded time series of cumulative samples, one sorted list per
    key.

    The registry's counters are process-cumulative; windowed rates
    need history. The evaluator records ``(t, value)`` on every tick;
    :meth:`delta` bisects for the latest sample at or before
    ``now - window`` (falling back to the oldest — partial coverage
    beats no answer). Samples older than ``max_age_s`` are pruned on
    write, and a series exceeding ``max_samples`` COARSENS its older
    half (every other sample dropped) — windowed deltas only need
    anchors, not full resolution, so a month-long budget window costs
    kilobytes per series, not the raw 5-second-tick history.
    """

    def __init__(self, max_age_s, max_samples=4096):
        self.max_age_s = float(max_age_s)
        self.max_samples = max(8, int(max_samples))
        self._series = {}
        self._lock = threading.Lock()

    def record(self, key, t, value):
        t = float(t)
        with self._lock:
            arr = self._series.get(key)
            if arr is None:
                arr = self._series.setdefault(key, [])
            arr.append((t, float(value)))
            horizon = t - self.max_age_s
            if len(arr) > 2 and arr[1][0] < horizon:
                # keep ONE sample older than the horizon so a
                # full-width window still has an anchor to diff against
                idx = bisect.bisect_left(arr, (horizon, -1e308)) - 1
                if idx > 0:
                    del arr[:idx]
            if len(arr) > self.max_samples:
                half = len(arr) // 2
                arr[:half] = arr[0:half:2]

    def delta(self, key, window_s, now=None):
        """``(delta, span_s)`` of the newest sample vs the anchor at
        ``now - window_s`` (oldest sample when coverage is partial);
        None with fewer than two samples."""
        with self._lock:
            arr = self._series.get(key)
            if arr is None or len(arr) < 2:
                return None
            latest_t, latest_v = arr[-1]
            cut = (now if now is not None else latest_t) - float(window_s)
            i = bisect.bisect_right(arr, (cut, 1e308)) - 1
            anchor_t, anchor_v = arr[max(0, i)]
        span = latest_t - anchor_t
        if span <= 0:
            return None
        return latest_v - anchor_v, span

    def latest(self, key):
        with self._lock:
            arr = self._series.get(key)
            return arr[-1][1] if arr else None

    def keys(self):
        with self._lock:
            return list(self._series)


def _match_labels(labelnames, values, match):
    if not match:
        return True
    labels = dict(zip(labelnames, values))
    return all(labels.get(k) == str(v) for k, v in match.items())


class SLO:
    """One declared objective: a name, a target, and the recipe for
    reading its raw series off a :class:`~.registry.MetricsRegistry`.
    Subclasses implement :meth:`sample` (cumulative values recorded
    each tick) plus the kind-specific evaluation below."""

    kind = "ratio"

    def __init__(self, name, target, description="", registry=None):
        self.name = str(name)
        self.target = float(target)
        self.description = description
        self.registry = registry if registry is not None else REGISTRY

    def sample(self):
        """``{series_suffix: cumulative_value}`` to record this tick."""
        raise NotImplementedError

    def describe(self):
        return {"kind": self.kind, "target": self.target,
                "description": self.description}


class RatioSLO(SLO):
    """good/total objective. Subclasses implement :meth:`good_total`
    returning the two CUMULATIVE series."""

    kind = "ratio"

    def good_total(self):
        raise NotImplementedError

    def sample(self):
        good, total = self.good_total()
        return {"good": good, "total": total}

    def sli(self, store, window_s, now=None):
        """Good fraction over the window (None without enough data or
        with zero traffic in the window — no traffic is not an SLI of
        1.0, it's the absence of one)."""
        g = store.delta(f"{self.name}:good", window_s, now)
        t = store.delta(f"{self.name}:total", window_s, now)
        if g is None or t is None or t[0] <= 0:
            return None
        return max(0.0, min(1.0, g[0] / t[0]))

    def burn_rate(self, store, window_s, now=None):
        """Error-budget burn multiple over the window (None when the
        SLI is unknown). A target of 1.0 makes any error an infinite
        burn — capped at 1e9 to stay JSON-able."""
        sli = self.sli(store, window_s, now)
        if sli is None:
            return None
        budget = 1.0 - self.target
        if budget <= 0:
            return 0.0 if sli >= 1.0 else 1e9
        return (1.0 - sli) / budget


class LatencySLO(RatioSLO):
    """Latency-quantile objective over a registry histogram family:
    ``target`` of requests must land at or under ``threshold_ms``
    (snapped UP to the nearest bucket boundary so good counts are
    exact cumulative-bucket reads, not interpolations).

    ``match`` filters children by label subset — per engine
    (``{"engine_id": ..., "stage": "total"}``), per serving bucket, or
    any other labeled slice the family carries.
    """

    def __init__(self, name, threshold_ms, target=0.99,
                 family="mxnet_tpu_serving_latency_ms", match=None,
                 description="", registry=None):
        super().__init__(name, target, description, registry)
        self.family = str(family)
        self.match = dict(match or {})
        self.threshold_ms = float(threshold_ms)

    def effective_bound(self):
        """The bucket boundary the threshold snapped up to (None when
        the family doesn't exist yet or the threshold exceeds every
        finite bucket — good then means "finished at all")."""
        fam = self.registry.get(self.family)
        if fam is None or not hasattr(fam, "buckets"):
            return None
        for b in fam.buckets:
            if b >= self.threshold_ms:
                return b
        return None

    def good_total(self):
        fam = self.registry.get(self.family)
        if fam is None or not hasattr(fam, "buckets"):
            return 0.0, 0.0
        idx = None
        for i, b in enumerate(fam.buckets):
            if b >= self.threshold_ms:
                idx = i
                break
        good = total = 0.0
        for values, child in fam._sorted_children():
            if not _match_labels(fam.labelnames, values, self.match):
                continue
            cum = child.cumulative()
            good += cum[idx] if idx is not None else cum[-1]
            total += child.count
        return good, total

    def exemplars(self, max_items=8):
        """The retrievable evidence for a violated latency objective:
        OpenMetrics exemplars recorded in buckets ABOVE the effective
        bound (i.e. requests that missed the objective), slowest
        first. Each carries the trace id a scraper resolves at
        ``/traces/<id>`` — exactly what a firing burn-rate alert
        links to."""
        fam = self.registry.get(self.family)
        if fam is None or not hasattr(fam, "buckets"):
            return []
        bound = self.effective_bound()
        out = []
        for values, child in fam._sorted_children():
            if not _match_labels(fam.labelnames, values, self.match):
                continue
            for b, ex in child.exemplars().items():
                if bound is not None and b <= bound:
                    continue        # met the objective: not evidence
                out.append({"trace_id": ex["trace_id"],
                            "value_ms": round(ex["value"], 3),
                            "bucket_le": ("+Inf" if b == float("inf")
                                          else b),
                            "ts": ex["ts"]})
        out.sort(key=lambda e: -e["value_ms"])
        return out[:int(max_items)]

    def describe(self):
        return dict(super().describe(), family=self.family,
                    match=self.match, threshold_ms=self.threshold_ms,
                    effective_threshold_ms=self.effective_bound())


class AvailabilitySLO(RatioSLO):
    """Availability objective over an outcome-labeled counter family:
    good = the ``good_events`` children, total = good + the
    ``bad_events`` children (sheds and errors burn budget; outcomes
    not named — e.g. in-flight bookkeeping — count for neither side).
    """

    def __init__(self, name, target=0.999,
                 family="mxnet_tpu_serving_requests_total", match=None,
                 good_events=("completed",),
                 bad_events=("failed", "expired", "rejected_queue_full",
                             "rejected_too_long", "rejected_stopped",
                             "cancelled"),
                 event_label="event", description="", registry=None):
        super().__init__(name, target, description, registry)
        self.family = str(family)
        self.match = dict(match or {})
        self.good_events = tuple(good_events)
        self.bad_events = tuple(bad_events)
        self.event_label = event_label

    def good_total(self):
        fam = self.registry.get(self.family)
        if fam is None:
            return 0.0, 0.0
        good = bad = 0.0
        for values, child in fam._sorted_children():
            if not _match_labels(fam.labelnames, values, self.match):
                continue
            event = dict(zip(fam.labelnames, values)).get(self.event_label)
            if event in self.good_events:
                good += child.value
            elif event in self.bad_events:
                bad += child.value
        return good, good + bad

    def describe(self):
        return dict(super().describe(), family=self.family,
                    match=self.match, good_events=list(self.good_events),
                    bad_events=list(self.bad_events),
                    event_label=self.event_label)


class ThresholdSLO(SLO):
    """Bound-comparison objective: a windowed value must stay at-or-
    under (``op="le"``) or at-or-over (``op="ge"``) ``target``.
    Subclasses implement :meth:`value`. ``burn_rate`` is the violation
    multiple (1.0 = exactly at the bound) so threshold objectives plug
    into the same alert rules as ratio ones."""

    kind = "threshold"

    def __init__(self, name, target, op="le", description="",
                 registry=None):
        if op not in ("le", "ge"):
            raise ValueError(f"threshold op must be le/ge, got {op!r}")
        super().__init__(name, target, description, registry)
        self.op = op

    def value(self, store, window_s, now=None):
        raise NotImplementedError

    def ok(self, value):
        if value is None:
            return None
        return value <= self.target if self.op == "le" \
            else value >= self.target

    def burn_rate(self, store, window_s, now=None):
        v = self.value(store, window_s, now)
        if v is None:
            return None
        if self.op == "le":
            return v / self.target if self.target > 0 else 1e9
        return self.target / v if v > 0 else 1e9

    def budget_remaining(self, value):
        """Headroom to the bound as a fraction of the bound (negative
        = violating) — the threshold analog of error budget."""
        if value is None or self.target == 0:
            return None
        if self.op == "le":
            return (self.target - value) / self.target
        return (value - self.target) / self.target

    def describe(self):
        return dict(super().describe(), op=self.op)


class CostSLO(ThresholdSLO):
    """Cost budget: device seconds per 1k valid tokens over the
    window, read as the delta ratio of two cumulative counter
    families (the serving cost ledger's)."""

    def __init__(self, name, budget_s_per_1k,
                 seconds_family="mxnet_tpu_serving_cost_seconds_total",
                 tokens_family="mxnet_tpu_serving_cost_tokens_total",
                 match=None, kinds=("device",), kind_label="kind",
                 description="", registry=None):
        super().__init__(name, budget_s_per_1k, op="le",
                         description=description, registry=registry)
        self.seconds_family = str(seconds_family)
        self.tokens_family = str(tokens_family)
        self.match = dict(match or {})
        self.kinds = tuple(kinds)
        self.kind_label = kind_label

    def _sum(self, family, want_kinds):
        fam = self.registry.get(family)
        if fam is None:
            return 0.0
        out = 0.0
        for values, child in fam._sorted_children():
            if not _match_labels(fam.labelnames, values, self.match):
                continue
            if want_kinds:
                kind = dict(zip(fam.labelnames, values)) \
                    .get(self.kind_label)
                if kind not in self.kinds:
                    continue
            out += child.value
        return out

    def sample(self):
        return {"seconds": self._sum(self.seconds_family, True),
                "tokens": self._sum(self.tokens_family, False)}

    def value(self, store, window_s, now=None):
        s = store.delta(f"{self.name}:seconds", window_s, now)
        t = store.delta(f"{self.name}:tokens", window_s, now)
        if s is None or t is None or t[0] <= 0:
            return None
        return s[0] * 1e3 / t[0]

    def describe(self):
        return dict(super().describe(), family=self.seconds_family,
                    tokens_family=self.tokens_family, match=self.match,
                    kinds=list(self.kinds),
                    budget_s_per_1k_tokens=self.target)


class GaugeSLO(ThresholdSLO):
    """Instantaneous-value objective: a callable (or a gauge family
    sum) compared against the bound — e.g. the router's routable-
    engine fraction. Windowless: the latest sampled value decides."""

    def __init__(self, name, target, op="ge", value_fn=None, family=None,
                 match=None, description="", registry=None):
        super().__init__(name, target, op=op, description=description,
                         registry=registry)
        if value_fn is None and family is None:
            raise ValueError("GaugeSLO needs value_fn or family")
        self.value_fn = value_fn
        self.family = str(family) if family is not None else None
        self.match = dict(match or {})

    def _read(self):
        if self.value_fn is not None:
            try:
                return float(self.value_fn())
            except Exception:
                return float("nan")
        fam = self.registry.get(self.family)
        if fam is None:
            return float("nan")
        return sum(child.value
                   for values, child in fam._sorted_children()
                   if _match_labels(fam.labelnames, values, self.match))

    def sample(self):
        return {"value": self._read()}

    def value(self, store, window_s, now=None):
        v = store.latest(f"{self.name}:value")
        if v is None or v != v:        # never sampled, or NaN read
            return None
        return v


class SloEvaluator:
    """The per-owner (engine / router) objective set + sample store.

    ``tick()`` samples every objective's cumulative series;
    ``snapshot()`` answers the ``/slo`` endpoint: per objective the
    SLI (or value), burn rates over the canonical windows, and error
    budget remaining over the budget window — and mirrors them onto
    the ``mxnet_tpu_slo_*`` gauge families so Grafana plots budgets
    and burns without scraping JSON.
    """

    def __init__(self, owner_id, registry=None, budget_s=None,
                 scale=None):
        self.owner_id = str(owner_id)
        reg = registry if registry is not None else REGISTRY
        self._scale = float(scale) if scale is not None else window_scale()
        self.budget_s = (float(budget_s) if budget_s is not None
                         else envvars.get("MXNET_TPU_SLO_BUDGET_S")
                         * self._scale)
        self.windows = OrderedDict(
            (label, s * self._scale) for label, s in BURN_WINDOWS.items())
        self.store = SampleStore(max_age_s=max(
            self.budget_s, max(self.windows.values())) * 1.25)
        self.objectives = OrderedDict()
        self._start_mono = time.monotonic()
        self._lock = threading.Lock()
        self._g_target = reg.gauge(
            "mxnet_tpu_slo_objective",
            "declared SLO target (ratio objectives) or bound "
            "(threshold objectives)", ("slo",))
        self._g_budget = reg.gauge(
            "mxnet_tpu_slo_error_budget_remaining",
            "error budget remaining over the budget window (1 = "
            "untouched, 0 = spent, negative = blown)", ("slo",))
        self._g_burn = reg.gauge(
            "mxnet_tpu_slo_burn_rate",
            "error-budget burn-rate multiple per trailing window "
            "(1 = sustainable)", ("slo", "window"))

    @property
    def scale(self):
        """The window multiplier every duration here was scaled by
        (drills shrink hours to seconds through it)."""
        return self._scale

    def window_s(self, w):
        """Resolve a window spec — a canonical label (``"5m"``…) or
        raw pre-scale seconds — into scaled seconds."""
        if isinstance(w, str):
            return self.windows[w]
        return float(w) * self._scale

    def _label(self, slo):
        return f"{self.owner_id}:{slo.name}"

    def add(self, slo):
        with self._lock:
            if slo.name in self.objectives:
                raise ValueError(f"SLO {slo.name!r} already declared")
            self.objectives[slo.name] = slo
        self._g_target.labels(slo=self._label(slo)).set(slo.target)
        return slo

    def get(self, name):
        with self._lock:
            return self.objectives.get(name)

    def tick(self, now=None):
        """Sample every objective's series into the store."""
        now = time.monotonic() if now is None else now
        with self._lock:
            objectives = list(self.objectives.values())
        for slo in objectives:
            try:
                samples = slo.sample()
            except Exception:
                continue        # one broken reader must not stop the rest
            for suffix, value in samples.items():
                self.store.record(f"{slo.name}:{suffix}", now, value)
        return now

    def burn(self, name, window_s, now=None):
        slo = self.get(name)
        if slo is None:
            return None
        return slo.burn_rate(self.store, window_s, now)

    def _budget_window(self, now):
        return min(self.budget_s, max(1e-9, now - self._start_mono))

    def evaluate(self, slo, now=None):
        """One objective's full answer (the /slo row)."""
        now = time.monotonic() if now is None else now
        budget_w = self._budget_window(now)
        out = {"objective": slo.name, **slo.describe(),
               "budget_window_s": round(budget_w, 3)}
        burns = {}
        for label, w in self.windows.items():
            b = slo.burn_rate(self.store, w, now)
            burns[label] = round(b, 4) if b is not None else None
        out["burn_rates"] = burns
        if slo.kind == "ratio":
            sli = slo.sli(self.store, budget_w, now)
            out["sli"] = round(sli, 6) if sli is not None else None
            budget = 1.0 - slo.target
            if sli is None:
                eb = None
            elif budget <= 0:
                eb = 1.0 if sli >= 1.0 else 0.0
            else:
                eb = 1.0 - (1.0 - sli) / budget
            out["error_budget_remaining"] = (round(eb, 6)
                                             if eb is not None else None)
            out["met"] = sli is None or sli >= slo.target
        else:
            value = slo.value(self.store, budget_w, now)
            out["value"] = (round(value, 6) if value is not None
                            else None)
            eb = slo.budget_remaining(value)
            out["error_budget_remaining"] = (round(eb, 6)
                                             if eb is not None else None)
            ok = slo.ok(value)
            out["met"] = True if ok is None else bool(ok)
        label = self._label(slo)
        if out["error_budget_remaining"] is not None:
            self._g_budget.labels(slo=label) \
                .set(out["error_budget_remaining"])
        for wlabel, b in burns.items():
            if b is not None:
                self._g_burn.labels(slo=label, window=wlabel).set(b)
        return out

    def snapshot(self, now=None, tick=True):
        """The ``/slo`` body. ``tick=True`` samples first, so a
        scrape right after startup still has something to diff."""
        now = time.monotonic() if now is None else now
        if tick:
            self.tick(now)
        with self._lock:
            objectives = list(self.objectives.values())
        return {"owner": self.owner_id,
                "budget_s": self.budget_s,
                "window_scale": self._scale,
                "windows_s": {k: round(v, 3)
                              for k, v in self.windows.items()},
                "uptime_s": round(now - self._start_mono, 3),
                "objectives": {slo.name: self.evaluate(slo, now)
                               for slo in objectives}}


# -- retro replay ------------------------------------------------------------
#
# A page is a claim: "the budget was burning 14.4× too fast over both
# windows". Retro replay AUDITS the claim after the fact: a frozen
# history window (the forensics section :mod:`.history` puts in every
# incident's flight bundle) is mounted as a read-only registry whose
# clock can be set, the objectives and alert rules are reconstructed
# from their own describe() rows, and the whole SLO pipeline re-runs
# over the stored samples — if the live decision doesn't reproduce
# from the persisted evidence, either the evidence or the alerting is
# broken, and both are worth a postmortem of their own.


class _ReplayChild:
    """One labeled series mounted as a counter/gauge child: ``.value``
    is the stored step-function value at the registry's current
    replay time (0 before the first sample — a cumulative counter
    that didn't exist yet had counted nothing)."""

    def __init__(self, reg, points):
        self._reg = reg
        self._points = points       # sorted [(t, v), ...]

    @property
    def value(self):
        i = bisect.bisect_right(self._points,
                                (self._reg.now, 1e308)) - 1
        return self._points[i][1] if i >= 0 else 0.0


class _ReplayHistChild:
    """One label-set's bucket series mounted as a histogram child:
    mirrors the live ``Histogram._Child`` read API
    (``cumulative()``/``count``) at the replay clock."""

    def __init__(self, reg, bucket_points):
        # bucket_points: [(le_float, sorted points)] ascending,
        # +Inf LAST (the live cumulative() contract)
        self._reg = reg
        self._buckets = bucket_points

    def _at(self, points):
        i = bisect.bisect_right(points, (self._reg.now, 1e308)) - 1
        return points[i][1] if i >= 0 else 0.0

    def cumulative(self):
        vals = [self._at(p) for _, p in self._buckets]
        # stored scrapes can land mid-update; re-impose monotonicity
        # so threshold reads never see cum[i] > cum[i+1]
        for i in range(1, len(vals)):
            vals[i] = max(vals[i], vals[i - 1])
        return vals

    @property
    def count(self):
        return self.cumulative()[-1]

    def exemplars(self):
        return {}                   # history stores values, not traces


class _ReplayFamily:
    def __init__(self, labelnames, children, buckets=None):
        self.labelnames = labelnames
        self._children = children   # [(values_tuple, child), ...]
        if buckets is not None:
            self.buckets = buckets  # histograms only (hasattr contract)

    def _sorted_children(self):
        return list(self._children)


class _ReplayRegistry:
    """A frozen history window mounted as a read-only registry with a
    settable clock: ``get(family)`` returns families whose children
    answer at ``self.now``, so the UNMODIFIED SLO/rule readers
    (:meth:`LatencySLO.good_total` & co.) replay the past verbatim."""

    def __init__(self, series):
        from .expo import parse_labels
        self.now = 0.0
        groups = {}         # name -> {labels_tuple: points}
        for key, pts in (series or {}).items():
            name, labels = parse_labels(key)
            points = sorted((float(t), float(v)) for t, v in pts)
            groups.setdefault(name, {})[
                tuple(sorted(labels.items()))] = points
        self._families = {}
        hist_bases = set()
        for name, children in groups.items():
            if name.endswith("_bucket") and any(
                    "le" in dict(lab) for lab in children):
                base = name[:-len("_bucket")]
                hist_bases.add(base)
                self._families[base] = self._build_hist(base, children)
        for name, children in groups.items():
            if name[:-len("_bucket")] in hist_bases \
                    and name.endswith("_bucket"):
                continue
            self._families.setdefault(
                name, self._build_flat(children))

    @staticmethod
    def _labelnames(children, drop=()):
        names = set()
        for lab in children:
            names.update(k for k, _ in lab)
        return tuple(sorted(names - set(drop)))

    def _build_flat(self, children):
        labelnames = self._labelnames(children)
        rows = []
        for lab, points in sorted(children.items()):
            d = dict(lab)
            values = tuple(d.get(k, "") for k in labelnames)
            rows.append((values, _ReplayChild(self, points)))
        return _ReplayFamily(labelnames, rows)

    def _build_hist(self, base, children):
        labelnames = self._labelnames(children, drop=("le",))
        grouped = {}        # non-le values -> {le_float: points}
        for lab, points in children.items():
            d = dict(lab)
            le = d.pop("le", None)
            if le is None:
                continue
            try:
                bound = float(le)
            except ValueError:
                continue
            values = tuple(d.get(k, "") for k in labelnames)
            grouped.setdefault(values, {})[bound] = points
        finite = sorted({b for les in grouped.values() for b in les
                         if b != float("inf")})
        rows = []
        for values, les in sorted(grouped.items()):
            ordered = [(b, les.get(b, [])) for b in finite]
            ordered.append((float("inf"), les.get(float("inf"), [])))
            rows.append((values, _ReplayHistChild(self, ordered)))
        return _ReplayFamily(labelnames, rows, buckets=tuple(finite))

    def set_time(self, t):
        self.now = float(t)

    def get(self, name):
        return self._families.get(name)

    def times(self):
        """Every distinct sample time in the window, ascending."""
        out = set()
        for fam in self._families.values():
            for _, child in fam._children:
                pts = (child._points if hasattr(child, "_points")
                       else [p for _, ps in child._buckets for p in ps])
                out.update(t for t, _ in pts)
        return sorted(out)


def _rebuild_objective(name, row, registry):
    """One describe() row back into a live SLO object (None when the
    kind can't replay — e.g. a value_fn-backed GaugeSLO whose callable
    died with the process)."""
    target = row.get("target")
    match = dict(row.get("match") or {})
    if row.get("threshold_ms") is not None:
        return LatencySLO(name, row["threshold_ms"], target=target,
                          family=row["family"], match=match,
                          registry=registry)
    if row.get("good_events") is not None:
        return AvailabilitySLO(
            name, target=target, family=row["family"], match=match,
            good_events=tuple(row["good_events"]),
            bad_events=tuple(row.get("bad_events") or ()),
            event_label=row.get("event_label", "event"),
            registry=registry)
    if row.get("budget_s_per_1k_tokens") is not None:
        return CostSLO(name, row["budget_s_per_1k_tokens"],
                       seconds_family=row["family"],
                       tokens_family=row["tokens_family"], match=match,
                       kinds=tuple(row.get("kinds") or ("device",)),
                       registry=registry)
    return None


def _rebuild_rule(row, registry):
    from . import alerts as _alerts
    kind = row.get("kind")
    name = row.get("alert")
    sev = row.get("severity", _alerts.TICKET)
    for_s = float(row.get("for_s") or 0.0)
    if kind == "burn_rate":
        return _alerts.BurnRateRule(
            name, row["slo"], long_window=row["long_window"],
            short_window=row["short_window"], factor=row["factor"],
            severity=sev, for_s=for_s)
    if kind == "threshold":
        return _alerts.ThresholdRule(
            name, row["slo"], window=row["window"],
            factor=row["factor"], severity=sev, for_s=for_s)
    if kind == "absence":
        return _alerts.AbsenceRule(
            name, row["family"], window=row["window"],
            match=dict(row.get("match") or {}), severity=sev,
            for_s=for_s, registry=registry)
    return None


def _norm_window_spec(w):
    """describe() stringifies windows; map back to a label the
    evaluator resolves, or raw pre-scale seconds."""
    w = str(w)
    if w in BURN_WINDOWS:
        return w
    try:
        return float(w)
    except ValueError:
        return w


def replay_history(window, objectives=None, rules=None, at=None,
                   scale=None, max_ticks=2000):
    """Re-judge a frozen history window: did the alerting decision
    reproduce from the persisted evidence?

    ``window`` is a forensics freeze (what :meth:`~.history.
    HistoryScraper.forensics` returns and the flight bundle's
    ``history_<owner>.json`` section carries — a whole bundle section
    replays its newest freeze). ``objectives``/``rules`` default to
    the ``objectives``/``alerts`` snapshots frozen alongside the
    series; pass explicit describe rows to replay what-if variants.
    ``at`` is the judgment instant (default: the freeze end — the
    moment the incident opened).

    Returns per-objective evaluations at ``at`` plus, per rule, the
    replayed ``active`` verdict against the frozen live state and a
    ``reproduces`` bool; ``skipped`` lists what could not be
    reconstructed (e.g. callable-backed gauges)."""
    if isinstance(window, dict) and "freezes" in window:
        if not window["freezes"]:
            raise ValueError("bundle section has no freezes")
        window = window["freezes"][-1]
    series = (window or {}).get("series") or {}
    obj_snap = objectives if objectives is not None \
        else window.get("objectives")
    rule_snap = rules if rules is not None else window.get("alerts")
    obj_rows = obj_snap or {}
    if isinstance(obj_rows, dict) and "objectives" in obj_rows:
        obj_rows = obj_rows["objectives"]
    rule_rows = rule_snap or ()
    if isinstance(rule_rows, dict):
        rule_rows = rule_rows.get("rules") or ()
    if scale is None:
        for snap in (obj_snap, rule_snap):
            if isinstance(snap, dict) \
                    and snap.get("window_scale") is not None:
                scale = float(snap["window_scale"])
                break
    scale = 1.0 if scale is None else float(scale)

    adapter = _ReplayRegistry(series)
    owner = str((window or {}).get("owner") or "window")
    # a private registry keeps replay's slo-gauge mirrors out of the
    # live process exposition
    from .registry import MetricsRegistry
    evaluator = SloEvaluator(f"replay:{owner}",
                             registry=MetricsRegistry(), scale=scale)
    skipped = []
    for name, row in dict(obj_rows).items():
        slo = _rebuild_objective(name, dict(row or {}), adapter)
        if slo is None:
            skipped.append({"objective": name,
                            "reason": "kind not replayable"})
        else:
            evaluator.add(slo)
    built_rules = []
    for row in rule_rows:
        row = dict(row or {})
        rule = _rebuild_rule(row, adapter)
        if rule is None:
            skipped.append({"rule": row.get("alert"),
                            "reason": "kind not replayable"})
            continue
        for attr in ("long_window", "short_window", "window"):
            if hasattr(rule, attr):
                setattr(rule, attr,
                        _norm_window_spec(getattr(rule, attr)))
        built_rules.append((rule, row))

    at = float(at) if at is not None \
        else float((window or {}).get("end") or 0.0)
    times = [t for t in adapter.times() if t <= at]
    if len(times) > max_ticks:
        stride = -(-len(times) // max_ticks)
        times = times[::stride] + ([times[-1]]
                                   if times[-1] not in times[::stride]
                                   else [])
    for t in times:
        adapter.set_time(t)
        evaluator.tick(t)
        for rule, _ in built_rules:
            rule.sample(evaluator, t)
    adapter.set_time(at)

    out_objectives = {}
    with evaluator._lock:
        live = list(evaluator.objectives.values())
    for slo in live:
        out_objectives[slo.name] = evaluator.evaluate(slo, at)
    out_rules = []
    reproduced = True
    for rule, row in built_rules:
        try:
            active, detail = rule.condition(evaluator, at)
        except Exception as e:
            active, detail = None, {"error": repr(e)}
        live_state = row.get("state")
        entry = {"alert": rule.name, "kind": rule.kind,
                 "severity": rule.severity,
                 "active": active, "detail": detail,
                 "live_state": live_state}
        if live_state is not None:
            live_active = live_state in ("pending", "firing")
            entry["reproduces"] = bool(active) == live_active
            reproduced = reproduced and entry["reproduces"]
        out_rules.append(entry)
    return {"owner": owner, "at": round(at, 3), "scale": scale,
            "ticks": len(times),
            "start": (window or {}).get("start"),
            "end": (window or {}).get("end"),
            "objectives": out_objectives,
            "rules": out_rules,
            "reproduces": reproduced,
            "skipped": skipped}
