"""Flight recorder + stall watchdog: crash-time self-diagnosis.

When a serving worker loop wedges mid-forward, a dist_async server
hangs in an optimizer update, or the process dies on an unhandled
exception, stderr alone says nothing about WHERE the time went. This
module keeps a bounded in-memory ring of recent run events (a tap on
:mod:`.events`), and on demand — watchdog trip, unhandled crash,
``SIGUSR2`` — dumps a post-mortem bundle for offline triage::

    <MXNET_TPU_FLIGHT_DIR or ./mxnet_tpu_flight>/<utc>-<pid>-<reason>/
        meta.json      reason, pid, argv, wall/mono stamps
        spans.json     kept + in-flight traces from the span ring
        events.jsonl   recent structured events (newest last)
        metrics.json   full registry snapshot
        threads.txt    stack trace of every live thread
        <extra>.json   one per registered bundle section
                       (add_bundle_section — e.g. the serving
                       router's router_scoreboard.json fleet view);
                       a section whose name carries an extension
                       (e.g. the continuous profiler's profile.txt)
                       is written verbatim when its fn returns text

The WATCHDOG is one daemon thread polling registered probes (a probe
returns None when healthy, or an anomaly dict). Subsystems register
their own: the serving engine reports a stalled worker loop and a
saturated-queue-with-no-dispatch; the dist_async worker reports an RPC
stuck in flight; the parameter server reports a stalled handle. A trip
emits a ``watchdog_anomaly`` event, bumps
``mxnet_tpu_watchdog_anomalies_total{kind=...}``, and dumps a bundle
(rate-limited per reason so a persistent stall can't fill the disk).

Env knobs: ``MXNET_TPU_FLIGHT_DIR`` (bundle root),
``MXNET_TPU_WATCHDOG=0`` (disable the thread),
``MXNET_TPU_WATCHDOG_INTERVAL_S`` (poll period, default 5),
``MXNET_TPU_WATCHDOG_STALL_S`` (stall threshold probes share,
default 30).
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
import traceback
from collections import deque

from .. import envvars
from . import events as _events
from . import spans as _spans
from .registry import REGISTRY

__all__ = ["FlightRecorder", "RECORDER", "install", "dump",
           "register_probe", "unregister_probe", "configure",
           "stall_seconds", "watchdog", "add_bundle_section",
           "remove_bundle_section", "set_meta_stamp"]

_dump_seq = itertools.count()

#: optional ``() -> dict | None`` merged into every bundle's meta.json
#: at write AND amend time — the incident tracker stamps the open
#: incident's id here so a bundle names the outage it belongs to
_meta_stamp = None


def set_meta_stamp(fn):
    """Register (or with None remove) the bundle meta stamper."""
    global _meta_stamp
    _meta_stamp = fn


def _stamp_meta(meta):
    """Apply the registered stamp without clobbering existing keys (a
    re-stamp at amend time keeps the id the bundle was born with)."""
    fn = _meta_stamp
    if fn is None:
        return meta
    try:
        extra = fn()
    except Exception:
        return meta
    if extra:
        for k, v in extra.items():
            meta.setdefault(k, v)
    return meta

_config = {
    "interval_s": envvars.get("MXNET_TPU_WATCHDOG_INTERVAL_S"),
    "stall_s": envvars.get("MXNET_TPU_WATCHDOG_STALL_S"),
    "min_dump_interval_s": 60.0,
    "recent_events": 512,
}


def stall_seconds():
    """The shared stall threshold watchdog probes compare against."""
    return _config["stall_s"]


def _thread_stacks():
    """Every live thread's current stack, formatted for threads.txt.
    Threads are listed by NAME (mxlint's thread-hygiene pass makes
    every framework thread carry one — ``mxnet_tpu_<subsystem>_<role>``)
    so a bundle attributes each stack to its subsystem at a glance."""
    frames = sys._current_frames()
    threads = sorted(threading.enumerate(), key=lambda t: t.name)
    lines = [f"# {len(threads)} live threads "
             f"({sum(1 for t in threads if t.daemon)} daemon), "
             f"sorted by name", ""]
    for t in threads:
        lines.append(f"--- thread {t.name} (ident={t.ident}, "
                     f"daemon={t.daemon}, alive={t.is_alive()}) ---")
        frame = frames.get(t.ident)
        if frame is None:
            lines.append("  <no frame>")
        else:
            lines.extend(l.rstrip("\n")
                         for l in traceback.format_stack(frame))
        lines.append("")
    return "\n".join(lines)


class FlightRecorder:
    """Recent-history ring + post-mortem bundle writer."""

    def __init__(self, out_dir=None):
        self._out_dir = out_dir
        self._recent = deque(maxlen=_config["recent_events"])
        self._lock = threading.Lock()
        self._last_dump = {}            # reason -> monotonic stamp
        self._last_bundle = None        # (monotonic, path) of newest
        # serializes bundle writes + amends; REENTRANT because a
        # SIGUSR2 handler may fire on the main thread mid-dump
        self._write_lock = threading.RLock()
        self._installed = False
        self._prev_excepthook = None
        self._prev_threading_hook = None
        self._sections = {}             # name -> () -> JSON-able dict

    @property
    def out_dir(self):
        return (self._out_dir
                or envvars.get("MXNET_TPU_FLIGHT_DIR")
                or os.path.join(os.getcwd(), "mxnet_tpu_flight"))

    # -- event tap ---------------------------------------------------------
    def _tap(self, rec):
        self._recent.append(rec)        # deque.append is atomic

    def recent_events(self):
        return list(self._recent)

    # -- extra bundle sections ---------------------------------------------
    def add_section(self, name, fn):
        """Register ``fn: () -> JSON-able`` written as ``<name>.json``
        into every future bundle — subsystems contribute their own
        post-mortem state (the serving router registers its fleet
        scoreboard here, so a wedged-engine trip explains the whole
        fleet, not just this process). A name that already carries an
        extension ("profile.txt") is used verbatim, and a section fn
        returning a string is written as raw text — the continuous
        profiler's collapsed-stack dump rides bundles this way."""
        with self._lock:
            self._sections[str(name)] = fn

    def remove_section(self, name):
        with self._lock:
            self._sections.pop(str(name), None)

    def get_section(self, name):
        """The registered section fn (or None) — lets an owner verify
        a shared section name is still ITS registration before
        removing it."""
        with self._lock:
            return self._sections.get(str(name))

    # -- install -----------------------------------------------------------
    def install(self, sigusr2=True, excepthook=True):
        """Attach the event tap + crash hooks (idempotent). SIGUSR2
        installation silently degrades off the main thread / platforms
        without the signal."""
        with self._lock:
            if self._installed:
                return self
            self._installed = True
        _events.add_tap(self._tap)
        if excepthook:
            self._prev_excepthook = sys.excepthook

            def _hook(exc_type, exc, tb):
                try:
                    self.dump("crash", extra={
                        "exception": "".join(traceback.format_exception(
                            exc_type, exc, tb))[-8000:]})
                except Exception:
                    pass
                (self._prev_excepthook or sys.__excepthook__)(
                    exc_type, exc, tb)

            sys.excepthook = _hook
            self._prev_threading_hook = threading.excepthook

            def _thook(args):
                try:
                    self.dump("thread_crash", extra={
                        "thread": getattr(args.thread, "name", "?"),
                        "exception": "".join(traceback.format_exception(
                            args.exc_type, args.exc_value,
                            args.exc_traceback))[-8000:]})
                except Exception:
                    pass
                if self._prev_threading_hook is not None:
                    self._prev_threading_hook(args)

            threading.excepthook = _thook
        if sigusr2:
            try:
                import signal
                signal.signal(signal.SIGUSR2,
                              lambda signo, frame:
                              self.dump("sigusr2", min_interval_s=0.0))
            except (ValueError, OSError, AttributeError):
                pass        # not main thread / no SIGUSR2 here
        return self

    # -- bundle ------------------------------------------------------------
    def dump(self, reason, extra=None, min_interval_s=None):
        """Write one post-mortem bundle; returns its directory, or
        None when rate-limited for this reason. Never raises — a
        diagnosis path must not add a second failure.

        Bundles DEDUPE across reasons within the rate-limit window: a
        watchdog trip and a page-alert firing seconds apart describe
        the same incident, so the second trigger AMENDS the existing
        bundle's meta (``causes`` grows, extras merge) instead of
        racing to write a near-identical sibling. An explicit
        ``min_interval_s=0`` (SIGUSR2, tests) always writes fresh."""
        if min_interval_s is None:
            min_interval_s = _config["min_dump_interval_s"]
        with self._write_lock:
            now = time.monotonic()
            with self._lock:
                last = self._last_dump.get(reason)
                if last is not None and now - last < min_interval_s:
                    return None
                self._last_dump[reason] = now
                lb = self._last_bundle
            if (min_interval_s > 0 and lb is not None
                    and now - lb[0] < min_interval_s):
                amended = self._amend(lb[1], reason, extra)
                if amended is not None:
                    return amended
            path = self._write_bundle(reason, extra)
            if path is not None:
                with self._lock:
                    self._last_bundle = (now, path)
            return path

    def _amend(self, path, reason, extra):
        """Tag an existing bundle with an additional cause; None when
        the bundle is gone — the caller writes a fresh one instead.
        The new trigger's extras land NAMESPACED under ``amendments``
        (keyed by reason) — a flat merge would overwrite the first
        trigger's payload under the same key (two page alerts both
        carry ``alert``)."""
        try:
            meta_path = os.path.join(path, "meta.json")
            with open(meta_path) as f:
                meta = json.load(f)
            causes = meta.setdefault("causes", [meta.get("reason")])
            causes.append(reason)
            if extra:
                meta.setdefault("amendments", []).append(
                    dict(extra, reason=reason))
            # a bundle amended mid-incident gains the incident id even
            # when the FIRST trigger predated the incident opening
            _stamp_meta(meta)
            tmp = meta_path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(meta, f, indent=2, default=str)
            os.replace(tmp, meta_path)
            _events.emit("flight_recorder_amend", reason=reason,
                         path=path, causes=causes)
            return path
        except Exception:
            return None

    def _write_bundle(self, reason, extra):
        try:
            stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
            # per-process sequence keeps names unique across dumps in
            # the same second (rate-limit 0 in tests), so the atomic
            # rename below never collides with an existing bundle
            path = os.path.join(
                self.out_dir,
                f"{stamp}-{os.getpid()}-{next(_dump_seq)}-{reason}")
            # write into a hidden temp dir, rename when complete: a
            # bundle directory that is VISIBLE is always whole (triage
            # tooling — and the tests — never see half a dump)
            tmp = path + ".tmp"
            os.makedirs(tmp, exist_ok=True)
            meta = {"reason": reason, "causes": [reason],
                    "ts": round(time.time(), 6),
                    "mono": round(time.monotonic(), 6),
                    "pid": os.getpid(), "argv": sys.argv,
                    "python": sys.version.split()[0]}
            if extra:
                meta.update(extra)
            _stamp_meta(meta)
            with open(os.path.join(tmp, "meta.json"), "w") as f:
                json.dump(meta, f, indent=2, default=str)
            with open(os.path.join(tmp, "spans.json"), "w") as f:
                json.dump(_spans.RECORDER.dump_state(), f, default=str)
            with open(os.path.join(tmp, "events.jsonl"), "w") as f:
                for rec in self.recent_events():
                    f.write(json.dumps(rec, default=str) + "\n")
            with open(os.path.join(tmp, "metrics.json"), "w") as f:
                json.dump(REGISTRY.snapshot(), f, default=str)
            with open(os.path.join(tmp, "threads.txt"), "w") as f:
                f.write(_thread_stacks())
            with self._lock:
                sections = list(self._sections.items())
            for name, fn in sections:
                try:        # a broken section must not lose the bundle
                    data = fn()
                    fname = name if "." in name else f"{name}.json"
                    with open(os.path.join(tmp, fname), "w") as f:
                        if isinstance(data, str):
                            f.write(data)
                        else:
                            json.dump(data, f, indent=2, default=str)
                except Exception:
                    pass
            os.rename(tmp, path)
            _events.emit("flight_recorder_dump", reason=reason, path=path)
            print(f"mxnet_tpu flight recorder: wrote {path} "
                  f"(reason: {reason})", file=sys.stderr)
            return path
        except Exception:
            return None


class Watchdog:
    """One daemon thread polling probes; trips emit + dump."""

    def __init__(self):
        self._probes = {}
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._c_anomalies = REGISTRY.counter(
            "mxnet_tpu_watchdog_anomalies_total",
            "watchdog-detected stalls/anomalies by kind", ("kind",))

    def register(self, name, probe):
        """Register ``probe: () -> None | dict`` and make sure the
        watchdog thread runs (unless MXNET_TPU_WATCHDOG=0)."""
        with self._lock:
            self._probes[name] = probe
            if (self._thread is None
                    and envvars.get("MXNET_TPU_WATCHDOG")):
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="mxnet_tpu_watchdog",
                    daemon=True)
                self._thread.start()

    def unregister(self, name):
        with self._lock:
            self._probes.pop(name, None)

    def _run(self):
        while not self._stop.wait(_config["interval_s"]):
            with self._lock:
                probes = list(self._probes.items())
            for name, probe in probes:
                try:
                    anomaly = probe()
                except Exception as e:   # a broken probe is itself news
                    anomaly = {"kind": "probe_error", "error": repr(e)}
                if not anomaly:
                    continue
                kind = anomaly.get("kind", name)
                self._c_anomalies.labels(kind=kind).inc()
                _events.emit("watchdog_anomaly", probe=name, **anomaly)
                RECORDER.dump(f"watchdog_{kind}")

    def stop(self):
        """Tests only: halt the poll thread."""
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)


#: process-wide flight recorder / watchdog singletons
RECORDER = FlightRecorder()
_WATCHDOG = Watchdog()


def watchdog():
    return _WATCHDOG


def install(sigusr2=True, excepthook=True):
    return RECORDER.install(sigusr2=sigusr2, excepthook=excepthook)


def dump(reason, extra=None, min_interval_s=0.0):
    return RECORDER.dump(reason, extra=extra,
                         min_interval_s=min_interval_s)


def register_probe(name, probe):
    _WATCHDOG.register(name, probe)


def unregister_probe(name):
    _WATCHDOG.unregister(name)


def add_bundle_section(name, fn):
    RECORDER.add_section(name, fn)


def remove_bundle_section(name):
    RECORDER.remove_section(name)


def configure(interval_s=None, stall_s=None, min_dump_interval_s=None,
              recent_events=None):
    """Runtime tuning (tests shrink the intervals to force fast
    trips). Only the arguments given change."""
    if interval_s is not None:
        _config["interval_s"] = float(interval_s)
    if stall_s is not None:
        _config["stall_s"] = float(stall_s)
    if min_dump_interval_s is not None:
        _config["min_dump_interval_s"] = float(min_dump_interval_s)
    if recent_events is not None:
        _config["recent_events"] = int(recent_events)
        RECORDER._recent = deque(RECORDER._recent,
                                 maxlen=_config["recent_events"])
    return dict(_config)
