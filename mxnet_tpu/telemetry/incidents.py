"""Correlated incident timeline: one object per outage, not N signals.

When an engine wedges, the fleet emits a burst of disjoint telemetry:
an alert walks pending→firing, the watchdog trips, the router
scoreboard marks the seat down, a flight bundle lands on disk, a
replacement seat warms up. Each is already observable on its own
surface; this module folds them into correlated **incident** objects
so ``/incidents`` answers the on-call question directly: *what is
happening, since when, and what evidence do I have*.

The :class:`IncidentTracker` is an event tap (no thread): it watches
the structured run-event stream for SIGNAL events —

- ``alert_state``          (the alert daemon's transitions; *firing*
  opens an incident, *resolved* releases it),
- ``watchdog_anomaly``     (stall/wedge trips — openers),
- ``router_engine_state``  (scoreboard transitions; *down* opens and
  holds the incident, *up* releases),
- ``engine_start`` / ``warmup_replay`` / ``router_engine_added`` /
  ``router_engine_removed`` (restart/recovery breadcrumbs — attach
  to an open incident, never open one),
- ``flight_recorder_dump`` / ``flight_recorder_amend`` (evidence:
  the bundle path links into the incident, and — the other
  direction — the recorder stamps the open incident's id into every
  bundle's ``meta.json`` via :func:`~.recorder.set_meta_stamp`).

An incident stays OPEN while any constituent alert is firing or any
seat it saw go down has not come back; once everything released, it
closes after a quiet ``MXNET_TPU_INCIDENT_GAP_S`` (scaled by
``MXNET_TPU_SLO_WINDOW_SCALE`` like every other judging-layer
duration). New signals inside the gap fold into the open incident —
one wedge produces ONE incident carrying the alert, the trip, the
scoreboard transition and the (single, deduped) bundle.

Served at ``/incidents`` on every exposition server (the default
route reads the process tracker; a router overrides with its fleet
merge). ``mxnet_tpu_incidents_total`` counts openings,
``mxnet_tpu_incidents_open`` gauges the live count.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

from .. import envvars
from . import events as _events
from . import recorder as _recorder
from .registry import REGISTRY

__all__ = ["Incident", "IncidentTracker", "TRACKER", "install",
           "snapshot", "open_incidents", "id_for_alert",
           "merge_snapshots"]

_incident_seq = itertools.count(1)

#: signal kinds that OPEN an incident (everything else only attaches)
_OPENERS = ("alert", "watchdog", "scoreboard")

#: run-event types the tap consumes (everything else returns in one
#: frozenset lookup — the tap rides the hot emit path)
_SIGNAL_EVENTS = frozenset((
    "alert_state", "watchdog_anomaly", "router_engine_state",
    "router_peer_state", "engine_start", "warmup_replay",
    "router_engine_added", "router_engine_removed",
    "flight_recorder_dump", "flight_recorder_amend"))


class Incident:
    """One correlated outage: signals, lifecycle, evidence."""

    __slots__ = ("id", "opened_ts", "opened_mono", "closed_ts",
                 "closed_mono", "last_signal_mono", "signals", "counts",
                 "firing", "down_engines", "engines", "alerts",
                 "bundles", "max_signals")

    def __init__(self, max_signals=128):
        self.id = f"inc-{os.getpid():x}-{next(_incident_seq)}"
        self.opened_ts = time.time()
        self.opened_mono = time.monotonic()
        self.closed_ts = None
        self.closed_mono = None
        self.last_signal_mono = self.opened_mono
        self.signals = deque(maxlen=max_signals)
        self.counts = {}            # kind -> count (never truncated)
        self.firing = set()         # (owner, alert) currently firing
        self.down_engines = set()
        self.engines = set()
        self.alerts = set()         # every alert that ever fired here
        self.bundles = []
        self.max_signals = max_signals

    @property
    def open(self):
        return self.closed_ts is None

    def add(self, kind, summary, engine_id=None, alert=None,
            bundle=None):
        now = time.monotonic()
        self.last_signal_mono = now
        self.counts[kind] = self.counts.get(kind, 0) + 1
        if engine_id:
            self.engines.add(str(engine_id))
        if alert:
            self.alerts.add(str(alert))
        if bundle and bundle not in self.bundles:
            self.bundles.append(bundle)
        self.signals.append({"kind": kind,
                             "ts": round(time.time(), 3),
                             "summary": summary})

    def releasable(self):
        """True when nothing holds the incident open anymore (only
        the quiet gap remains)."""
        return not self.firing and not self.down_engines

    def row(self):
        dur = ((self.closed_mono or time.monotonic())
               - self.opened_mono)
        return {"id": self.id,
                "state": "open" if self.open else "closed",
                "opened_ts": round(self.opened_ts, 3),
                "closed_ts": (round(self.closed_ts, 3)
                              if self.closed_ts else None),
                "duration_s": round(dur, 3),
                "counts": dict(self.counts),
                "signals": list(self.signals),
                "firing": sorted(f"{o}:{a}" for o, a in self.firing),
                "down_engines": sorted(self.down_engines),
                "engines": sorted(self.engines),
                "alerts": sorted(self.alerts),
                "bundles": list(self.bundles)}


class IncidentTracker:
    """Process-wide signal correlator (thread-free: driven entirely by
    the events tap; closing is evaluated lazily on signal/snapshot)."""

    def __init__(self, gap_s=None, keep_closed=32, registry=None):
        self._gap_override = gap_s
        self._lock = threading.Lock()
        self._open = []             # usually 0 or 1
        self._closed = deque(maxlen=keep_closed)
        self._installed = False
        self._total = 0
        self._registry = registry if registry is not None else REGISTRY
        self._c_total = None
        self._g_open = None

    @property
    def gap_s(self):
        if self._gap_override is not None:
            return float(self._gap_override)
        from .slo import window_scale
        return (envvars.get("MXNET_TPU_INCIDENT_GAP_S")
                * window_scale())

    # -- install -----------------------------------------------------------
    def install(self):
        """Attach the events tap + the recorder meta stamp (idempotent;
        called by engine/router ``start``). Registers the two incident
        families on first install."""
        reg = self._registry
        with self._lock:
            if self._installed:
                return self
            self._installed = True
            self._c_total = reg.counter(
                "mxnet_tpu_incidents_total", "incidents opened")
            self._g_open = reg.gauge(
                "mxnet_tpu_incidents_open", "incidents currently open")
            self._g_open.set(len(self._open))
        _events.add_tap(self._tap)
        _recorder.set_meta_stamp(self._meta_stamp)
        return self

    def uninstall(self):
        """Tests only: detach the tap/stamp (state is kept)."""
        with self._lock:
            self._installed = False
        _events.remove_tap(self._tap)
        _recorder.set_meta_stamp(None)

    def _meta_stamp(self):
        """The recorder hook: every flight bundle written while an
        incident is open carries its id in ``meta.json``."""
        with self._lock:
            closed = self._sweep_locked(time.monotonic())
            iid = self._open[-1].id if self._open else None
        self._emit_closed(closed)
        return {"incident_id": iid} if iid is not None else None

    # -- the tap (hot path: one frozenset lookup for non-signals) ----------
    def _tap(self, rec):
        event = rec.get("event")
        if event not in _SIGNAL_EVENTS:
            return
        try:
            self._signal(event, rec)
        except Exception:
            pass                    # telemetry must not hurt the emitter

    def _signal(self, event, rec):
        kind, summary, opener = self._classify(event, rec)
        if kind is None:
            return
        eid = rec.get("engine_id")
        alert = rec.get("alert")
        with self._lock:
            now = time.monotonic()
            closed = self._sweep_locked(now)
            inc = self._open[-1] if self._open else None
            if inc is None:
                if not opener:
                    return          # breadcrumbs never open incidents
                inc = Incident()
                self._open.append(inc)
                self._total += 1
                if self._c_total is not None:
                    self._c_total.inc()
                    self._g_open.set(len(self._open))
                opened = True
            else:
                opened = False
            inc.add(kind, summary, engine_id=eid, alert=alert,
                    bundle=rec.get("path") if kind == "bundle" else None)
            # holds/releases
            if kind == "alert":
                key = (rec.get("owner"), alert)
                if rec.get("to") == "firing":
                    inc.firing.add(key)
                elif rec.get("to") in ("resolved", "inactive"):
                    inc.firing.discard(key)
            elif kind == "scoreboard":
                if rec.get("state") == "down":
                    inc.down_engines.add(str(eid))
                else:
                    inc.down_engines.discard(str(eid))
            elif kind == "peer":
                # a dead peer ROUTER holds the incident open until the
                # survivor either adopts its orphans ("adopted") or
                # sees it return ("up") — handled beats ongoing
                key = f"peer:{rec.get('peer')}"
                if rec.get("state") == "down":
                    inc.down_engines.add(key)
                else:
                    inc.down_engines.discard(key)
            inc_id = inc.id
        self._emit_closed(closed)
        if opened:
            _events.emit("incident_open", incident_id=inc_id,
                         first_signal=kind)
            # forensics: freeze the PRECEDING history window NOW, so
            # the flight bundle written later — after the failure
            # developed — still shows what the fleet looked like
            # before the first signal
            try:
                from . import history as _history
                _history.on_incident_open(inc_id)
            except Exception:
                pass            # history must never hurt the tracker

    def _classify(self, event, rec):
        """(kind, summary, opens) for one signal event — None kind
        drops it (e.g. a pending alert with no incident open)."""
        if event == "alert_state":
            to = rec.get("to")
            if to not in ("pending", "firing", "resolved", "inactive"):
                return None, None, False
            return ("alert",
                    {"alert": rec.get("alert"), "owner": rec.get("owner"),
                     "severity": rec.get("severity"),
                     "from": rec.get("from"), "to": to},
                    to == "firing")
        if event == "watchdog_anomaly":
            return ("watchdog",
                    {k: rec.get(k) for k in ("probe", "kind",
                                             "seconds_since_beat",
                                             "queue_depth")
                     if rec.get(k) is not None}, True)
        if event == "router_engine_state":
            return ("scoreboard",
                    {"engine_id": rec.get("engine_id"),
                     "state": rec.get("state"),
                     "reason": rec.get("reason")},
                    rec.get("state") == "down")
        if event == "router_peer_state":
            return ("peer",
                    {"router_id": rec.get("router_id"),
                     "peer": rec.get("peer"),
                     "state": rec.get("state")},
                    rec.get("state") == "down")
        if event in ("flight_recorder_dump", "flight_recorder_amend"):
            return ("bundle", {"reason": rec.get("reason"),
                               "path": rec.get("path")}, False)
        # restart/recovery breadcrumbs
        return ("restart", {"event": event,
                            "engine_id": rec.get("engine_id")}, False)

    def _sweep_locked(self, now):
        """Close every open incident that released and has been quiet
        past the gap. Returns the closed ids; the close events are
        emitted OUTSIDE the lock by :meth:`_emit_closed` (an emit under
        the tracker lock would re-enter the tap chain holding it)."""
        gap = self.gap_s
        still, closed = [], []
        for inc in self._open:
            if inc.releasable() and now - inc.last_signal_mono > gap:
                inc.closed_ts = time.time()
                inc.closed_mono = now
                self._closed.append(inc)
                closed.append(inc.id)
            else:
                still.append(inc)
        if closed:
            self._open = still
            if self._g_open is not None:
                self._g_open.set(len(self._open))
        return closed

    @staticmethod
    def _emit_closed(closed_ids):
        for iid in closed_ids:
            _events.emit("incident_close", incident_id=iid)

    # -- read surfaces -----------------------------------------------------
    def open_incidents(self):
        with self._lock:
            closed = self._sweep_locked(time.monotonic())
            rows = [inc.row() for inc in self._open]
        self._emit_closed(closed)
        return rows

    def id_for_alert(self, owner, alert):
        """The open incident that saw this alert (notification
        enrichment: the page carries the incident id)."""
        with self._lock:
            closed = self._sweep_locked(time.monotonic())
            out = None
            for inc in reversed(self._open):
                if str(alert) in inc.alerts:
                    out = inc.id
                    break
            if out is None and self._open:
                out = self._open[-1].id
        self._emit_closed(closed)
        return out

    def snapshot(self):
        """The ``/incidents`` body: open incidents first (newest
        leading), then the recent closed ring."""
        with self._lock:
            swept = self._sweep_locked(time.monotonic())
            opens = [inc.row() for inc in reversed(self._open)]
            closed = [inc.row() for inc in reversed(self._closed)]
            total = self._total
        self._emit_closed(swept)
        return {"open": opens, "recent": closed,
                "total_opened": total,
                "gap_s": round(self.gap_s, 3)}

    def reset(self):
        """Tests only: drop all incident state."""
        with self._lock:
            self._open = []
            self._closed.clear()
            self._total = 0
            if self._g_open is not None:
                self._g_open.set(0)


#: the process tracker every exposition server's /incidents reads
TRACKER = IncidentTracker()


def install():
    return TRACKER.install()


def snapshot():
    return TRACKER.snapshot()


def open_incidents():
    return TRACKER.open_incidents()


def id_for_alert(owner, alert):
    return TRACKER.id_for_alert(owner, alert)


def merge_snapshots(parts):
    """Fold N ``/incidents`` bodies (the router's own + every scraped
    seat's) into one fleet view, deduped by incident id — in-process
    seats share the router's tracker, so their incidents appear once.
    ``parts`` is ``[(source_name_or_None, snapshot_or_None), ...]``."""
    seen = set()
    opens, recent = [], []
    total = 0
    sources = {}
    for source, snap in parts:
        name = source or "local"
        if not snap or "open" not in snap:
            if source is not None:
                sources[name] = "missing"
            continue
        sources[name] = "ok"
        total += snap.get("total_opened", 0)
        for dst, key in ((opens, "open"), (recent, "recent")):
            for row in snap.get(key, ()):
                if row.get("id") in seen:
                    continue
                seen.add(row.get("id"))
                if source is not None:
                    row = dict(row, source=name)
                dst.append(row)
    opens.sort(key=lambda r: -(r.get("opened_ts") or 0))
    recent.sort(key=lambda r: -(r.get("closed_ts") or 0))
    return {"open": opens, "recent": recent, "total_opened": total,
            "sources": sources}
