"""Hierarchical spans with tail-based sampling — the trace id grows a tree.

ISSUE 3 gave every request one flat trace id; this module decomposes a
traced request into Dapper-style spans (Sigelman et al., 2010): each
span has an id, a parent id, the trace id, a start stamp, a duration,
attributes, and an ok/error status. Parentage propagates through the
same contextvar machinery as the trace id (``span(...)`` nests), and
crosses the dist_async wire as a frame field so the server's handle
span parents under the worker's RPC span across processes.

Completed spans land in a bounded in-process ring buffer with
**tail-based sampling**: the keep/drop decision is made when a trace's
local-root span finishes, so only traces that turned out SLOW
(``slow_ms`` threshold), ERRORED, or explicitly forced (shed requests)
are retained in full — the rest are counted and dropped. At high QPS
the buffer therefore holds exactly the traces an operator wants to
open, not a random head sample.

Cost discipline: with spans disabled (``MXNET_TPU_SPANS=0`` or
``configure(enabled=False)``) every entry point is one global check
returning a shared no-op span — the instrumented hot paths stay inside
the disabled-path microbench guard (tests/test_spans.py). Enabled,
a span is a small object + one locked append at end.

Consumption: ``/traces`` + ``/traces/<id>`` on the exposition server
(:mod:`.expo`), Chrome-trace events merged into ``profiler.dump()``'s
stream, ``tools/telemetry_dump.py --traces / --trace <id>``, and the
flight-recorder bundle (:mod:`.recorder`).
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading
import time
from collections import OrderedDict

from .. import envvars
from .registry import REGISTRY
from .trace import (current_trace_id, new_trace_id, reset_trace_id,
                    set_trace_id)

__all__ = ["Span", "SpanRecorder", "RECORDER", "span", "start_span",
           "record_span", "use_span", "current_span", "current_span_id",
           "configure", "enabled", "traces_summary", "get_trace",
           "slowest_traces", "export_chrome_events", "reset",
           "merge_trace_records", "merge_trace_summaries",
           "mono_to_us", "perf_to_mono"]

_current_span = contextvars.ContextVar("mxnet_tpu_span", default=None)
_counter = itertools.count()

# perf_counter is the span clock (matches profiler.py's Chrome-trace
# microseconds); request timestamps are time.monotonic() — capture the
# offset once so synthesized spans land on the same axis
_MONO_OFFSET_US = (time.perf_counter_ns() // 1000
                   - int(time.monotonic() * 1e6))


def _now_us():
    return time.perf_counter_ns() // 1000


def mono_to_us(mono_s):
    """Map a ``time.monotonic()`` stamp onto the span/profiler
    microsecond axis."""
    return int(mono_s * 1e6) + _MONO_OFFSET_US


def perf_to_mono(perf_s):
    """Map a ``time.perf_counter()`` stamp onto the ``time.monotonic()``
    axis, in seconds. The two clocks share CLOCK_MONOTONIC on Linux
    but differ elsewhere (Windows < 3.13), so intervals timed with
    perf_counter must cross through this before being compared against
    monotonic wall endpoints."""
    return perf_s - _MONO_OFFSET_US / 1e6


def _new_span_id():
    from .trace import _process_salt
    return f"s{_process_salt()}-{os.getpid():x}-{next(_counter):x}"


class Span:
    """One timed operation in a trace tree.

    ``local_root=True`` marks the span whose completion triggers this
    process's tail-sampling decision for the trace — a span with no
    in-process parent (its ``parent_id`` may still name a REMOTE span,
    e.g. the worker RPC span a server handle parents under).
    """

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "ts_us",
                 "dur_us", "wall", "attrs", "status", "error", "pid",
                 "tid", "local_root", "forced", "_ended")

    def __init__(self, name, trace_id, parent_id=None, local_root=False,
                 attrs=None, forced=False, ts_us=None, wall=None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.local_root = local_root
        self.forced = forced
        self.attrs = dict(attrs) if attrs else {}
        self.ts_us = ts_us if ts_us is not None else _now_us()
        self.wall = wall if wall is not None else time.time()
        self.dur_us = None
        self.status = "ok"
        self.error = None
        self.pid = os.getpid()
        self.tid = threading.get_ident()
        self._ended = False
        if local_root:
            RECORDER.on_root_start(trace_id)

    def set_attr(self, **kv):
        self.attrs.update(kv)
        return self

    def force_keep(self):
        """Mark this span's trace keep-regardless (shed requests)."""
        self.forced = True
        return self

    def end(self, status=None, error=None, end_us=None):
        """Close the span (idempotent: first end wins) and hand it to
        the recorder for the tail-sampling bookkeeping."""
        if self._ended:
            return self
        self._ended = True
        self.dur_us = max(0, (end_us if end_us is not None else _now_us())
                          - self.ts_us)
        if status is not None:
            self.status = status
        if error is not None:
            self.error = error
            self.status = "error"
        RECORDER.record(self)
        return self

    @property
    def duration_ms(self):
        return None if self.dur_us is None else self.dur_us / 1e3

    def to_dict(self):
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "ts_us": self.ts_us, "dur_us": self.dur_us,
             "wall": round(self.wall, 6), "status": self.status,
             "pid": self.pid, "tid": self.tid}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.error:
            d["error"] = self.error
        return d

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id}, "
                f"id={self.span_id}, parent={self.parent_id})")


class _NoopSpan:
    """Shared do-nothing span handed out when spans are disabled — the
    instrumented paths call the same methods either way."""

    trace_id = None
    span_id = None
    parent_id = None
    name = None
    dur_us = None
    duration_ms = None
    status = "ok"

    def set_attr(self, **kv):
        return self

    def force_keep(self):
        return self

    def end(self, status=None, error=None, end_us=None):
        return self

    def to_dict(self):
        return {}


NOOP = _NoopSpan()


class SpanRecorder:
    """Bounded ring buffer of kept traces + the tail-sampling logic.

    Per trace this process accumulates finished spans in an ACTIVE
    buffer; when a local-root span finishes, the trace is KEPT (moved
    into the ring, evicting the oldest) if that root was slow, errored
    or forced — otherwise, once no local roots remain open, the spans
    are dropped and only a counter remembers them. Both buffers are
    bounded (``max_traces`` kept, ``max_active`` in flight,
    ``max_spans`` per trace) so a leaked trace can never grow the
    process.
    """

    def __init__(self, max_traces=None, slow_ms=None, max_spans=None,
                 max_active=None, registry=None):
        self.max_traces = int(max_traces
                              or envvars.get("MXNET_TPU_TRACE_BUFFER"))
        self.slow_ms = float(slow_ms if slow_ms is not None
                             else envvars.get("MXNET_TPU_TRACE_SLOW_MS"))
        self.max_spans = int(max_spans
                             or envvars.get("MXNET_TPU_TRACE_MAX_SPANS"))
        self.max_active = int(max_active
                              or envvars.get("MXNET_TPU_TRACE_MAX_ACTIVE"))
        self._lock = threading.Lock()
        self._active = OrderedDict()   # trace_id -> buf dict
        self._kept = OrderedDict()     # trace_id -> kept-trace dict
        self._dropped = 0
        reg = registry if registry is not None else REGISTRY
        self._c_traces = reg.counter(
            "mxnet_tpu_traces_total",
            "tail-sampling decisions on completed traces", ("decision",))
        self._c_spans = reg.counter(
            "mxnet_tpu_trace_spans_total", "spans recorded")

    # -- bookkeeping -------------------------------------------------------
    def _buf(self, trace_id):
        buf = self._active.get(trace_id)
        if buf is None:
            while len(self._active) >= self.max_active:
                self._active.popitem(last=False)   # evict oldest partial
            buf = {"spans": [], "open_roots": 0, "dropped_spans": 0,
                   "forced": False}
            self._active[trace_id] = buf
        return buf

    def on_root_start(self, trace_id):
        with self._lock:
            self._buf(trace_id)["open_roots"] += 1

    def record(self, sp):
        self._c_spans.inc()
        with self._lock:
            buf = self._buf(sp.trace_id)
            if len(buf["spans"]) < self.max_spans:
                buf["spans"].append(sp.to_dict())
            else:
                buf["dropped_spans"] += 1
            if sp.forced:
                buf["forced"] = True
            if not sp.local_root:
                return
            buf["open_roots"] -= 1
            slow = (sp.dur_us or 0) / 1e3 >= self.slow_ms
            err = sp.status == "error"
            keep = slow or err or sp.forced or buf["forced"]
            if keep:
                reason = ("error" if err else
                          "slow" if slow else "forced")
                self._keep(sp, buf, reason)
            if buf["open_roots"] <= 0:
                self._active.pop(sp.trace_id, None)
                if not keep:
                    rec = self._kept.get(sp.trace_id)
                    if rec is not None:
                        # an earlier root already KEPT this trace:
                        # late siblings merge into the kept record
                        # (bounded) instead of vanishing unaccounted
                        room = self.max_spans - len(rec["spans"])
                        if room > 0:
                            rec["spans"].extend(buf["spans"][:room])
                        rec["dropped_spans"] += (buf["dropped_spans"]
                                                 + max(0, len(buf["spans"])
                                                       - max(room, 0)))
                    else:
                        self._dropped += 1
                        self._c_traces.labels(decision="dropped").inc()

    def _keep(self, root, buf, reason):
        # called with the lock held
        rec = self._kept.pop(root.trace_id, None)
        if rec is None:
            rec = {"trace_id": root.trace_id, "spans": [],
                   "dropped_spans": 0, "status": "ok",
                   "duration_ms": 0.0, "root": root.name,
                   "wall": root.wall, "keep_reason": reason}
            self._c_traces.labels(decision="kept").inc()
        rec["spans"].extend(buf["spans"])
        rec["dropped_spans"] += buf["dropped_spans"]
        buf["spans"] = []              # a later root keep must not dup
        buf["dropped_spans"] = 0
        if root.status == "error":
            rec["status"] = "error"
        rec["duration_ms"] = max(rec["duration_ms"],
                                 round((root.dur_us or 0) / 1e3, 3))
        rec["root"] = root.name
        self._kept[root.trace_id] = rec          # refresh recency
        while len(self._kept) > self.max_traces:
            self._kept.popitem(last=False)

    # -- read side ---------------------------------------------------------
    def summary(self):
        """The /traces payload: config + per-kept-trace summaries
        (slowest first) + drop accounting."""
        with self._lock:
            kept = [{k: v for k, v in rec.items() if k != "spans"}
                    | {"spans": len(rec["spans"])}
                    for rec in self._kept.values()]
            active = len(self._active)
        kept.sort(key=lambda r: -r["duration_ms"])
        return {"slow_ms": self.slow_ms, "max_traces": self.max_traces,
                "kept": kept, "dropped_traces": self._dropped,
                "active_traces": active}

    def get(self, trace_id):
        """Full span list for one trace — kept ring first, then the
        in-flight buffer (flagged ``partial``)."""
        with self._lock:
            rec = self._kept.get(trace_id)
            if rec is not None:
                return dict(rec, spans=list(rec["spans"]))
            buf = self._active.get(trace_id)
            if buf is not None and buf["spans"]:
                return {"trace_id": trace_id, "partial": True,
                        "spans": list(buf["spans"]),
                        "dropped_spans": buf["dropped_spans"]}
        return None

    def slowest(self, n=3):
        """[(trace_id, root name, duration_ms)] — the per-leg bench
        summary and loadgen exit hint."""
        return [(r["trace_id"], r["root"], r["duration_ms"])
                for r in self.summary()["kept"][:n]]

    def chrome_events(self):
        """Kept (and in-flight) spans as Chrome trace-event dicts, on
        the same microsecond axis as profiler.py's stream."""
        with self._lock:
            spans = [s for rec in self._kept.values()
                     for s in rec["spans"]]
            spans += [s for buf in self._active.values()
                      for s in buf["spans"]]
        out = []
        for s in spans:
            ev = {"name": s["name"], "cat": "span", "ph": "X",
                  "ts": s["ts_us"], "dur": s["dur_us"] or 0,
                  "pid": s["pid"], "tid": s["tid"],
                  "args": {"trace_id": s["trace_id"],
                           "span_id": s["span_id"],
                           "parent_id": s["parent_id"],
                           "status": s["status"],
                           **s.get("attrs", {})}}
            out.append(ev)
        return out

    def dump_state(self):
        """Everything (kept + active) for the flight-recorder bundle."""
        with self._lock:
            return {"kept": [dict(r, spans=list(r["spans"]))
                             for r in self._kept.values()],
                    "active": {tid: {"spans": list(b["spans"]),
                                     "open_roots": b["open_roots"],
                                     "dropped_spans": b["dropped_spans"]}
                               for tid, b in self._active.items()},
                    "dropped_traces": self._dropped}

    def clear(self):
        with self._lock:
            self._active.clear()
            self._kept.clear()
            self._dropped = 0


#: process-wide recorder every instrumented layer records into
RECORDER = SpanRecorder()

_enabled = envvars.get("MXNET_TPU_SPANS")


def enabled():
    return _enabled


def configure(enabled=None, slow_ms=None, max_traces=None, max_spans=None,
              max_active=None):
    """Adjust span recording at runtime (tests, operators). Only the
    arguments given change; returns the active :class:`SpanRecorder`."""
    global _enabled
    if enabled is not None:
        _enabled = bool(enabled)
    if slow_ms is not None:
        RECORDER.slow_ms = float(slow_ms)
    if max_traces is not None:
        RECORDER.max_traces = int(max_traces)
    if max_spans is not None:
        RECORDER.max_spans = int(max_spans)
    if max_active is not None:
        RECORDER.max_active = int(max_active)
    return RECORDER


def reset():
    """Drop all recorded traces (test isolation)."""
    RECORDER.clear()


def current_span():
    """The innermost active Span on this context, or None."""
    return _current_span.get()


def current_span_id():
    sp = _current_span.get()
    return sp.span_id if sp is not None else None


def start_span(name, trace_id=None, parent_id=None, attrs=None,
               local_root=None, forced=False):
    """Start a MANUAL span (caller must ``.end()`` it — possibly from
    another thread; the serving request root crosses submit→worker).

    Parentage: explicit ``parent_id`` wins (pass the REMOTE span id
    from a wire frame with ``local_root=True``); otherwise the ambient
    context span. ``local_root`` defaults to "no in-process parent".
    """
    if not _enabled:
        return NOOP
    ctx_parent = _current_span.get()
    if (parent_id is None and ctx_parent is not None
            and (trace_id is None or trace_id == ctx_parent.trace_id)):
        # ambient parenting only within ONE trace: a request root
        # minted with its own trace id must not parent under an
        # unrelated ambient span (a fit step submitting requests)
        parent_id = ctx_parent.span_id
        trace_id = ctx_parent.trace_id
        root = False
    else:
        root = parent_id is None or ctx_parent is None
    if local_root is not None:
        root = local_root
    if trace_id is None:
        trace_id = current_trace_id() or new_trace_id("t")
    return Span(name, trace_id, parent_id=parent_id, local_root=root,
                attrs=attrs, forced=forced)


@contextlib.contextmanager
def span(name, **attrs):
    """``with span("stage", k=v) as sp:`` — scoped span parented under
    the ambient one; an exception ends it with error status (and
    re-raises). Mints + scopes a trace id when none is active, so
    events emitted inside correlate."""
    if not _enabled:
        yield NOOP
        return
    parent = _current_span.get()
    had_tid = current_trace_id()
    if parent is not None:
        sp = Span(name, parent.trace_id, parent_id=parent.span_id,
                  attrs=attrs)
    else:
        sp = Span(name, had_tid or new_trace_id("t"), local_root=True,
                  attrs=attrs)
    tok = _current_span.set(sp)
    ttok = set_trace_id(sp.trace_id) if had_tid is None else None
    try:
        yield sp
    except BaseException as e:
        sp.end(error=repr(e))
        raise
    else:
        sp.end()
    finally:
        _current_span.reset(tok)
        if ttok is not None:
            reset_trace_id(ttok)


@contextlib.contextmanager
def use_span(sp):
    """Adopt an existing span (and its trace id) as the ambient
    context WITHOUT ending it on exit — the server-side handle span
    wraps ``_handle`` this way so optimizer-update spans parent under
    it."""
    if sp is None or sp is NOOP or sp.span_id is None:
        yield sp
        return
    tok = _current_span.set(sp)
    ttok = set_trace_id(sp.trace_id)
    try:
        yield sp
    finally:
        _current_span.reset(tok)
        reset_trace_id(ttok)


def record_span(name, trace_id, parent_id=None, start_us=None, end_us=None,
                mono_start=None, mono_end=None, attrs=None, status="ok",
                error=None):
    """Record an already-timed interval as a completed span (the
    engine synthesizes per-request queue/pack/forward spans from stage
    stamps this way — batch stages time once, every member request's
    tree shows them). ``mono_*`` accept ``time.monotonic()`` stamps."""
    if not _enabled:
        return NOOP
    if start_us is None:
        start_us = mono_to_us(mono_start)
    if end_us is None:
        end_us = (mono_to_us(mono_end) if mono_end is not None
                  else _now_us())
    # deriving the wall STAMP of a past mono point (not a duration):
    # wall-now minus the mono offset since start is the only way to
    # wall-stamp a span recorded after the fact
    wall = time.time() - (_now_us() - start_us) / 1e6  # mxlint: disable=wall-clock-delta
    sp = Span(name, trace_id, parent_id=parent_id, local_root=False,
              attrs=attrs, ts_us=start_us, wall=wall)
    sp.end(status=status, error=error, end_us=end_us)
    return sp


# -- cross-ring merge (the router's fleet-wide /traces view) --------------
def _reanchor_spans(spans_out):
    """Re-anchor cross-PROCESS spans onto one time axis (the carried
    ROADMAP 'remote trace axes' follow-up).

    Each process records ``ts_us`` on its own perf_counter axis —
    exact within the process, meaningless across processes. Every span
    also carries a ``wall`` stamp. Per process group we estimate that
    process's axis offset as the median of ``wall*1e6 - ts_us`` over
    its spans, then shift every foreign group's spans onto the
    REFERENCE axis (the group owning the trace's root span — the
    router's, for router-front traces) by the offset difference.
    Groups key on ``(source ring, pid)``, not pid alone: two remote
    engines that are each pid 1 inside their own container must not
    pool their unrelated perf_counter axes (nor silently share the
    reference axis). A ring only ever holds spans recorded in its own
    process, so the source disambiguates pid collisions; the same
    process split across keys just computes the same offset twice.
    Intra-process timing stays perf_counter-exact (one rigid shift per
    group); cross-process alignment is as good as the hosts' wall
    clocks — sub-millisecond on one machine, which is what makes the
    merged tree render on one monotonic axis with no negative gaps.
    Returns the pids shifted (empty when everything already shared the
    reference axis)."""
    import statistics

    groups = {}
    for s in spans_out:
        if s.get("ts_us") is None or s.get("wall") is None:
            continue
        groups.setdefault((s.get("_src"), s.get("pid")), []).append(s)
    if len(groups) <= 1:
        return []
    ids = {s.get("span_id") for s in spans_out}
    roots = [s for s in spans_out
             if s.get("parent_id") not in ids and s.get("wall") is not None]
    if roots:
        root = min(roots, key=lambda s: s["wall"])
        ref = (root.get("_src"), root.get("pid"))
    else:
        ref = min(groups, key=str)
    if ref not in groups:
        ref = min(groups, key=str)
    offsets = {key: statistics.median(s["wall"] * 1e6 - s["ts_us"]
                                      for s in group)
               for key, group in groups.items()}
    shifted = set()
    for key, group in groups.items():
        if key == ref:
            continue
        shift = int(offsets[key] - offsets[ref])
        for s in group:
            s["ts_us"] += shift
        shifted.add(key[1])
    return sorted(shifted, key=str)


def merge_trace_records(parts):
    """Merge per-ring ``/traces/<id>`` records for ONE trace into a
    single span tree — the router's cross-engine trace aggregation.

    ``parts`` is ``[(tag, record_or_None), ...]``: each record is a
    :meth:`SpanRecorder.get`-shaped dict from one span ring (the
    router's own process ring, then each REMOTE engine's, scraped over
    its ``/traces/<id>`` endpoint). A non-None ``tag`` (the engine id)
    is stamped into each span's ``attrs.engine`` when the span doesn't
    already carry one, so the merged tree names the engine that served
    every span. Spans are deduped by span id (a request that visited
    the same ring twice must not double-render), statuses/durations
    combine pessimistically, and the record's ``engines`` lists every
    engine that contributed a span. Returns None when no part had the
    trace."""
    spans_out, seen = [], set()
    merged = None
    engines = set()
    for src_idx, (tag, rec) in enumerate(parts):
        if not rec:
            continue
        if merged is None:
            merged = {"trace_id": rec.get("trace_id"), "status": "ok",
                      "duration_ms": 0.0, "dropped_spans": 0,
                      "sources": 0}
        merged["sources"] += 1
        for s in rec.get("spans", ()):
            sid = s.get("span_id")
            if sid in seen:
                continue
            seen.add(sid)
            s = dict(s)
            s["_src"] = src_idx     # re-anchor group key; stripped below
            attrs = dict(s.get("attrs") or {})
            if tag and "engine" not in attrs:
                attrs["engine"] = tag
                s["attrs"] = attrs
            if attrs.get("engine"):
                engines.add(str(attrs["engine"]))
            spans_out.append(s)
        if rec.get("status") == "error":
            merged["status"] = "error"
        merged["duration_ms"] = max(merged["duration_ms"],
                                    rec.get("duration_ms") or 0.0)
        merged["dropped_spans"] += rec.get("dropped_spans", 0) or 0
        if rec.get("partial"):
            merged["partial"] = True
        if rec.get("keep_reason") and "keep_reason" not in merged:
            merged["keep_reason"] = rec["keep_reason"]
    if merged is None:
        return None
    # per-process perf_counter axes are re-anchored onto the ROOT
    # process's axis via wall stamps, so a merged cross-process tree
    # (and telemetry_dump's render of it) reads on ONE monotonic axis
    reanchored = _reanchor_spans(spans_out)
    if reanchored:
        merged["reanchored_pids"] = reanchored
    for s in spans_out:
        s.pop("_src", None)
    spans_out.sort(key=lambda s: (s.get("ts_us") or 0))
    ids = {s.get("span_id") for s in spans_out}
    roots = [s for s in spans_out if s.get("parent_id") not in ids]
    merged["root"] = roots[0]["name"] if roots else None
    merged["spans"] = spans_out
    merged["engines"] = sorted(engines)
    return merged


def merge_trace_summaries(parts):
    """Merge per-ring ``/traces`` summaries into one fleet summary:
    kept records union by trace id (a cross-engine trace appears once,
    with every contributing engine listed), drop/active counts sum.
    ``parts`` is ``[(tag, summary_or_None), ...]`` like
    :func:`merge_trace_records`."""
    by_tid = OrderedDict()
    out = {"slow_ms": None, "max_traces": None, "dropped_traces": 0,
           "active_traces": 0, "sources": 0}
    for tag, summary in parts:
        if not summary:
            continue
        out["sources"] += 1
        if out["slow_ms"] is None:
            out["slow_ms"] = summary.get("slow_ms")
            out["max_traces"] = summary.get("max_traces")
        out["dropped_traces"] += summary.get("dropped_traces", 0) or 0
        out["active_traces"] += summary.get("active_traces", 0) or 0
        for kept in summary.get("kept", ()):
            rec = by_tid.get(kept["trace_id"])
            if rec is None:
                rec = dict(kept)
                rec["engines"] = []
                by_tid[kept["trace_id"]] = rec
            else:
                rec["spans"] = (rec.get("spans") or 0) \
                    + (kept.get("spans") or 0)
                rec["duration_ms"] = max(rec.get("duration_ms") or 0.0,
                                         kept.get("duration_ms") or 0.0)
                if kept.get("status") == "error":
                    rec["status"] = "error"
                # the front door's root names the trace in a fleet view
                if kept.get("root") == "router/request":
                    rec["root"] = kept["root"]
            if tag and tag not in rec["engines"]:
                rec["engines"].append(tag)
    kept = sorted(by_tid.values(),
                  key=lambda r: -(r.get("duration_ms") or 0.0))
    out["kept"] = kept
    return out


# -- module-level read helpers (the expo server + tools consume these) ----
def traces_summary():
    return RECORDER.summary()


def get_trace(trace_id):
    return RECORDER.get(trace_id)


def slowest_traces(n=3):
    return RECORDER.slowest(n)


def export_chrome_events():
    return RECORDER.chrome_events()
