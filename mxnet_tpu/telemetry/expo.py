"""Telemetry exposition: a background-thread HTTP server.

Serves three endpoints off a stdlib ``ThreadingHTTPServer`` (no new
dependencies, daemon threads — never blocks process exit):

- ``/metrics``  — Prometheus text format 0.0.4 from a
  :class:`~.registry.MetricsRegistry` (default: the process registry);
- ``/healthz``  — liveness: 200 + JSON when the attached health check
  passes (serving worker alive, queue open), 503 when it fails, 200
  ``{"ok": true}`` when nothing registered a check (process is up);
- ``/stats``    — the attached component's JSON stats dict (a
  ``ServingEngine.snapshot()`` made scrapeable), falling back to the
  registry snapshot;
- ``/traces``   — tail-sampled trace summaries from the process span
  ring (:mod:`.spans`), slowest first, plus drop accounting;
- ``/traces/<id>`` — one trace's full span list (kept ring first,
  then in-flight partials), 404 when the id was dropped or never
  seen;
- ``POST /submit`` — optional dispatch endpoint (only when a
  ``submit_fn`` is attached): JSON body in, ``(status, JSON)`` out —
  how a :class:`~mxnet_tpu.serving.router.ServingRouter` drives a
  remote engine;
- ``/warmup`` — optional warmup-manifest endpoint (only when a
  ``warmup_fn`` is attached): the engine's visited-shape manifest /
  the router's fleet union, JSON — what a rolling restart replays
  before admitting traffic;
- ``/profile`` — the process continuous profiler's (:mod:`.profiling`)
  folded-stack dump as flamegraph-ready collapsed text;
  ``?format=json`` returns the top-self-time JSON summary instead;
- ``/costs`` — optional per-bucket cost ledger (only when a
  ``costs_fn`` is attached): the engine's device/compile-seconds +
  request/token table, or the router's fleet-merged cost table;
- ``/slo`` — optional SLO evaluator snapshot (only when an ``slo_fn``
  is attached): per objective the SLI/value, burn rates per canonical
  window and error budget remaining — the router serves the
  fleet-aggregated view;
- ``/alerts`` — optional alert-daemon state (only when an
  ``alerts_fn`` is attached): every rule's pending/firing/resolved
  position, burn-rate history, latency exemplars (trace ids
  retrievable at ``/traces/<id>``) and recent transitions;
- ``/incidents`` — the correlated incident timeline
  (:mod:`.incidents`): open incidents first, each folding the alert
  firings, watchdog trips, scoreboard transitions, restarts and
  flight bundles it correlates. Default: the process tracker; a
  router attaches ``incidents_fn`` for the fleet merge;
- ``/query_range`` — retrospective range queries over the process
  history store (:mod:`.history`): ``?family=...&start=&end=&step=``
  with ``fn=value|rate|increase|quantile`` (+ ``q=99`` percentile,
  ``window=`` trailing seconds, any other param a label matcher) —
  what ``tools/mxtop.py`` polls;
- ``/series`` — the history store's series listing (keys, labels,
  per-tier point counts, covered range).

A server constructed with ``metrics_fn``/``traces_fn``/``trace_fn``
overrides serves those endpoints from the callables instead of the
process registry/span ring — the router's AGGREGATED fleet view is
exactly such a server.

Attach points: ``ServingEngine.expose(port)``,
``ServingRouter.expose(port)`` and ``kvstore.expose_telemetry(kv,
port)`` construct one of these; scripts can also run
``start_server(port)`` for bare registry exposition.

Also here: :func:`parse_prometheus_text`, the scrape-side parser the
loadgen cross-check and ``tools/telemetry_dump.py`` share, and
:func:`merge_prometheus_texts`, the scrape-merge the router's
aggregated ``/metrics`` is built on.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .registry import REGISTRY, _fmt

__all__ = ["TelemetryServer", "start_server", "parse_prometheus_text",
           "parse_labels", "parse_exemplar", "histogram_quantile",
           "merge_prometheus_texts"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class TelemetryServer:
    """Background /metrics + /healthz + /stats server.

    Parameters
    ----------
    registry : MetricsRegistry, default the process-wide one.
    healthz_fn : ``() -> (bool, dict)`` liveness check; None = always
        healthy (the process answered, that IS liveness).
    stats_fn : ``() -> dict`` for /stats; None = registry snapshot.
    metrics_fn : ``() -> str`` overriding /metrics (the router serves
        its aggregated fleet exposition this way); None = render the
        registry.
    traces_fn / trace_fn : ``() -> dict`` / ``(trace_id) -> dict|None``
        overriding /traces and /traces/<id>; None = the process span
        ring.
    submit_fn : ``(payload_dict) -> (status, body_dict)`` enabling
        ``POST /submit`` (remote engine dispatch); None = 404.
    warmup_fn : ``() -> dict | None`` enabling ``/warmup`` (the
        warmup manifest a restarting engine replays); None = 404.
    costs_fn : ``() -> dict`` enabling ``/costs`` (the serving cost
        ledger: per-bucket device/compile seconds + requests/tokens,
        or the router's fleet merge); None = 404.
    slo_fn : ``() -> dict`` enabling ``/slo`` (the owner's SLO
        evaluator snapshot: per objective the SLI/value, burn rates
        per window, error budget remaining — or the router's fleet
        aggregation); None = 404.
    alerts_fn : ``() -> dict`` enabling ``/alerts`` (the alert
        daemon's rule table: state machine position per rule, burn
        history, exemplars, recent transitions); None = 404.
    incidents_fn : ``() -> dict`` overriding ``/incidents`` (the
        router's fleet-merged incident timeline); None = the process
        incident tracker.
    history_fn : a :class:`~.history.HistoryStore` (or ``() ->
        store``) backing ``/query_range`` and ``/series``; None = the
        process's first live history scraper's store (404 when the
        history subsystem is off).
    whyslow_fn : ``() -> dict`` enabling ``/whyslow`` (the owner's
        per-stage latency-attribution table from
        :mod:`.attribution`, or the router's fleet merge); None =
        404.
    capture_fn : ``() -> dict`` enabling ``/capture`` (the owner's
        traffic-capture corpus summary from
        :mod:`~..serving.capture`, or the router's fleet merge);
        None = 404 (capture disabled).
    shadow_fn : ``() -> dict`` enabling ``/shadow`` (the router's
        shadow-diff verdict from :mod:`~..serving.shadow`); None =
        404 (shadow validation disabled).
    profile_fn : ``() -> str | dict`` overriding ``/profile``; None =
        the process continuous profiler (:mod:`.profiling`) — a str
        serves as collapsed text, a dict as JSON.
    port : 0 picks a free port (read it back from ``.port``).
    host : bind interface; loopback by default — exposing metrics on
        all interfaces is an operator decision, not a default.
    """

    def __init__(self, registry=None, healthz_fn=None, stats_fn=None,
                 metrics_fn=None, traces_fn=None, trace_fn=None,
                 submit_fn=None, warmup_fn=None, costs_fn=None,
                 profile_fn=None, slo_fn=None, alerts_fn=None,
                 incidents_fn=None, history_fn=None, whyslow_fn=None,
                 capture_fn=None, shadow_fn=None,
                 port=0, host="127.0.0.1"):
        self.registry = registry if registry is not None else REGISTRY
        self.healthz_fn = healthz_fn
        self.stats_fn = stats_fn
        self.metrics_fn = metrics_fn
        self.traces_fn = traces_fn
        self.trace_fn = trace_fn
        self.submit_fn = submit_fn
        self.warmup_fn = warmup_fn
        self.costs_fn = costs_fn
        self.profile_fn = profile_fn
        self.slo_fn = slo_fn
        self.alerts_fn = alerts_fn
        self.incidents_fn = incidents_fn
        self.history_fn = history_fn
        self.whyslow_fn = whyslow_fn
        self.capture_fn = capture_fn
        self.shadow_fn = shadow_fn
        server = self

        class Handler(BaseHTTPRequestHandler):
            # HTTP/1.1: chunked transfer (the streamed /submit path)
            # does not exist in 1.0 — a spec-following client/proxy
            # would ignore the header and see raw chunk framing. Every
            # non-chunked reply sets Content-Length, so 1.1 keep-alive
            # semantics stay correct.
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):      # scrapes must not spam stderr
                pass

            def do_GET(self):
                try:
                    server._route(self)
                except (BrokenPipeError, ConnectionResetError):
                    pass                    # scraper went away mid-reply

            def do_POST(self):
                try:
                    server._route_post(self)
                except (BrokenPipeError, ConnectionResetError):
                    pass                    # client went away mid-reply

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="mxnet_tpu_telemetry",
                                        daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self._httpd.server_address[1]

    @property
    def host(self):
        return self._httpd.server_address[0]

    def url(self, path="/metrics"):
        return f"http://{self.host}:{self.port}{path}"

    def _route(self, handler):
        path, _, query = handler.path.partition("?")
        if path == "/metrics":
            try:
                text = (self.metrics_fn() if self.metrics_fn is not None
                        else self.registry.render_prometheus())
            except Exception as e:
                self._reply(handler, 500, "text/plain",
                            f"# metrics error: {e!r}\n".encode())
                return
            self._reply(handler, 200, PROMETHEUS_CONTENT_TYPE,
                        text.encode())
        elif path == "/healthz":
            ok, detail = True, {}
            if self.healthz_fn is not None:
                try:
                    ok, detail = self.healthz_fn()
                except Exception as e:
                    ok, detail = False, {"error": repr(e)}
            body = json.dumps({"ok": bool(ok), **detail}).encode()
            self._reply(handler, 200 if ok else 503, "application/json",
                        body)
        elif path == "/stats":
            try:
                stats = (self.stats_fn() if self.stats_fn is not None
                         else self.registry.snapshot())
                body = json.dumps(stats, default=str).encode()
            except Exception as e:
                self._reply(handler, 500, "application/json",
                            json.dumps({"error": repr(e)}).encode())
                return
            self._reply(handler, 200, "application/json", body)
        elif path == "/traces" or path.startswith("/traces/"):
            from urllib.parse import unquote

            from . import spans as _spans
            if path == "/traces" or path == "/traces/":
                summary = (self.traces_fn() if self.traces_fn is not None
                           else _spans.traces_summary())
                body = json.dumps(summary, default=str).encode()
                self._reply(handler, 200, "application/json", body)
                return
            tid = unquote(path[len("/traces/"):])
            trace = (self.trace_fn(tid) if self.trace_fn is not None
                     else _spans.get_trace(tid))
            if trace is None:
                self._reply(handler, 404, "application/json",
                            json.dumps({"error": "unknown trace",
                                        "trace_id": tid}).encode())
                return
            self._reply(handler, 200, "application/json",
                        json.dumps(trace, default=str).encode())
        elif path == "/warmup":
            if self.warmup_fn is None:
                self._reply(handler, 404, "application/json",
                            json.dumps({"error": "no warmup manifest"})
                            .encode())
                return
            try:
                manifest = self.warmup_fn()
            except Exception as e:
                self._reply(handler, 500, "application/json",
                            json.dumps({"error": repr(e)}).encode())
                return
            self._reply(handler, 200, "application/json",
                        json.dumps(manifest, default=str).encode())
        elif path == "/profile":
            from urllib.parse import parse_qs
            params = parse_qs(query)
            want_json = params.get("format", [""])[0] == "json"
            try:
                if self.profile_fn is not None:
                    payload = self.profile_fn()
                else:
                    from . import profiling as _profiling
                    payload = (_profiling.profile_snapshot(
                        int(params.get("top", ["20"])[0]))
                        if want_json else _profiling.collapsed_text())
            except Exception as e:
                self._reply(handler, 500, "application/json",
                            json.dumps({"error": repr(e)}).encode())
                return
            if isinstance(payload, str):
                self._reply(handler, 200, "text/plain; charset=utf-8",
                            payload.encode())
            else:
                self._reply(handler, 200, "application/json",
                            json.dumps(payload, default=str).encode())
        elif path == "/costs":
            self._json_fn(handler, self.costs_fn, "no cost ledger")
        elif path == "/slo":
            self._json_fn(handler, self.slo_fn, "no SLO evaluator")
        elif path == "/alerts":
            self._json_fn(handler, self.alerts_fn, "no alert daemon")
        elif path == "/whyslow":
            self._json_fn(handler, self.whyslow_fn,
                          "no stage attribution")
        elif path == "/capture":
            self._json_fn(handler, self.capture_fn,
                          "traffic capture disabled")
        elif path == "/shadow":
            self._json_fn(handler, self.shadow_fn,
                          "shadow validation disabled")
        elif path == "/incidents":
            if self.incidents_fn is not None:
                self._json_fn(handler, self.incidents_fn, "")
                return
            # default: the process incident tracker — every exposition
            # server answers the on-call question, not just routers
            from . import incidents as _incidents
            self._json_fn(handler, _incidents.snapshot, "")
        elif path == "/series":
            store = self._history_store()
            self._json_fn(handler,
                          store.series if store is not None else None,
                          "no history store")
        elif path == "/query_range":
            self._query_range(handler, query)
        else:
            self._reply(handler, 404, "text/plain",
                        b"try /metrics, /healthz, /stats, /traces, "
                        b"/profile, /costs, /slo, /alerts, /incidents, "
                        b"/whyslow, /query_range, /series or /warmup\n")

    def _history_store(self):
        """Resolve the ``/query_range``/``/series`` backing store:
        the attached one (store or callable), else the process's
        first live history scraper (mirrors ``/incidents``'s
        process-default)."""
        store = self.history_fn
        if callable(store):
            store = store()
        if store is None:
            from . import history as _history
            store = _history.default_store()
        return store

    def _query_range(self, handler, query):
        """``/query_range?family=...&start=&end=&step=&fn=rate&q=99&
        window=&<label>=<value>`` — range evaluation over the history
        store. Unknown params are label matchers, so tenant/engine
        slicing needs no special syntax."""
        store = self._history_store()
        if store is None:
            self._reply(handler, 404, "application/json",
                        json.dumps({"error": "no history store"})
                        .encode())
            return
        from urllib.parse import parse_qs
        params = {k: v[-1] for k, v in parse_qs(query).items()}
        name = params.pop("family", None) or params.pop("name", None)
        if not name:
            self._reply(handler, 400, "application/json",
                        json.dumps({"error": "family= is required"})
                        .encode())
            return
        try:
            kw = {}
            for key in ("start", "end", "step", "window", "q"):
                if key in params:
                    kw[key] = float(params.pop(key))
            kw["fn"] = params.pop("fn", "value")
            if kw["fn"] not in ("value", "rate", "increase",
                                "quantile"):
                raise ValueError(f"unknown fn {kw['fn']!r}")
            body = store.query_range(name, match=params, **kw)
        except ValueError as e:
            self._reply(handler, 400, "application/json",
                        json.dumps({"error": str(e)}).encode())
            return
        except Exception as e:
            self._reply(handler, 500, "application/json",
                        json.dumps({"error": repr(e)}).encode())
            return
        self._reply(handler, 200, "application/json",
                    json.dumps(body).encode())

    def _json_fn(self, handler, fn, missing):
        """Serve an optional JSON endpoint off a callable: 404 when
        nothing is attached, 500 (never a hang-up) when it raises."""
        if fn is None:
            self._reply(handler, 404, "application/json",
                        json.dumps({"error": missing}).encode())
            return
        try:
            body = fn()
        except Exception as e:
            self._reply(handler, 500, "application/json",
                        json.dumps({"error": repr(e)}).encode())
            return
        self._reply(handler, 200, "application/json",
                    json.dumps(body, default=str).encode())

    def _route_post(self, handler):
        path = handler.path.split("?", 1)[0]
        if path != "/submit" or self.submit_fn is None:
            self._reply(handler, 404, "application/json",
                        json.dumps({"ok": False,
                                    "error": "no submit endpoint"})
                        .encode())
            return
        try:
            length = int(handler.headers.get("Content-Length", 0))
            payload = json.loads(handler.rfile.read(length).decode())
        except Exception as e:
            self._reply(handler, 400, "application/json",
                        json.dumps({"ok": False, "error_type": "BadRequest",
                                    "error": repr(e)}).encode())
            return
        try:
            code, body = self.submit_fn(payload)
        except Exception as e:   # the handler must answer, not hang up
            code, body = 500, {"ok": False,
                               "error_type": type(e).__name__,
                               "error": str(e)}
        if isinstance(body, dict):
            self._reply(handler, code, "application/json",
                        json.dumps(body, default=str).encode())
            return
        # a PART ITERATOR (streamed decode dispatch): chunked JSON
        # lines, one per generated token, the final body last — the
        # HTTP fallback for peers without the binary wire. A client
        # hanging up mid-stream closes the generator; the engine keeps
        # generating (parts are advisory, the future is authoritative).
        handler.send_response(code)
        handler.send_header("Content-Type", "application/jsonl")
        handler.send_header("Transfer-Encoding", "chunked")
        handler.end_headers()
        try:
            for part in body:
                data = (json.dumps(part, default=str) + "\n").encode()
                handler.wfile.write(
                    f"{len(data):x}\r\n".encode() + data + b"\r\n")
                handler.wfile.flush()
            handler.wfile.write(b"0\r\n\r\n")
        finally:
            close = getattr(body, "close", None)
            if close is not None:
                close()

    @staticmethod
    def _reply(handler, code, ctype, body):
        handler.send_response(code)
        handler.send_header("Content-Type", ctype)
        handler.send_header("Content-Length", str(len(body)))
        handler.end_headers()
        handler.wfile.write(body)

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_server(port=0, host="127.0.0.1", registry=None, healthz_fn=None,
                 stats_fn=None):
    """Convenience: start and return a :class:`TelemetryServer`."""
    return TelemetryServer(registry=registry, healthz_fn=healthz_fn,
                           stats_fn=stats_fn, port=port, host=host)


def _split_exemplar(line):
    """Split an exposition line at the OpenMetrics exemplar marker
    (`` # `` outside quoted label values) → ``(sample_part,
    exemplar_part_or_None)``. A parser that treats the whole line as
    one sample drops every exemplar-bearing series — the bug that made
    scrape-merge corrupt exemplar expositions."""
    in_quote = False
    prev = ""
    for i, ch in enumerate(line):
        if ch == '"' and prev != "\\":
            in_quote = not in_quote
        elif (ch == "#" and not in_quote and i > 0
                and line[i - 1] == " "):
            return line[:i - 1].rstrip(), line[i + 1:].strip()
        prev = ch if not (ch == "\\" and prev == "\\") else ""
    return line, None


def _parse_sample_line(line):
    """One exposition sample line → ``(key, float, exemplar_raw)`` or
    None (comment, blank, malformed). Splits the value at the last
    space OUTSIDE a quoted label value; an OpenMetrics exemplar
    (``... # {trace_id="..."} v ts``) is split off first and returned
    verbatim so round-trips keep it."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    line, exemplar = _split_exemplar(line)
    in_quote = False
    split_at = -1
    prev = ""
    for i, ch in enumerate(line):
        if ch == '"' and prev != "\\":
            in_quote = not in_quote
        elif ch == " " and not in_quote:
            split_at = i
        prev = ch if not (ch == "\\" and prev == "\\") else ""
    if split_at < 0:
        return None
    key, val = line[:split_at], line[split_at + 1:].strip()
    try:
        return key, float(val), exemplar
    except ValueError:
        return None


def parse_exemplar(raw):
    """An exemplar's raw text (``{trace_id="..."} 93.1 1690.5``) →
    ``{"labels": {...}, "trace_id": ..., "value": float, "ts":
    float|None}`` (None when malformed)."""
    if not raw:
        return None
    raw = raw.strip()
    if not raw.startswith("{"):
        return None
    depth_end = -1
    in_quote = False
    prev = ""
    for i, ch in enumerate(raw):
        if ch == '"' and prev != "\\":
            in_quote = not in_quote
        elif ch == "}" and not in_quote:
            depth_end = i
            break
        prev = ch if not (ch == "\\" and prev == "\\") else ""
    if depth_end < 0:
        return None
    _, labels = parse_labels("x" + raw[:depth_end + 1])
    rest = raw[depth_end + 1:].split()
    try:
        value = float(rest[0]) if rest else None
    except ValueError:
        return None
    ts = None
    if len(rest) > 1:
        try:
            ts = float(rest[1])
        except ValueError:
            ts = None
    if value is None:
        return None
    return {"labels": labels, "trace_id": labels.get("trace_id"),
            "value": value, "ts": ts}


def parse_prometheus_text(text, exemplars=None):
    """Parse exposition text into ``{name{labels}: float}`` (labels
    part verbatim, ``""`` for none). Inverse enough of
    ``MetricsRegistry.render_prometheus`` for scrape cross-checks —
    handles escaped quotes in label values, skips comments, and keeps
    the sample when an OpenMetrics exemplar trails it. Pass a dict as
    ``exemplars`` to collect ``{series_key: parsed_exemplar}`` for the
    series that carry one."""
    out = {}
    for line in text.splitlines():
        parsed = _parse_sample_line(line)
        if parsed is not None:
            out[parsed[0]] = parsed[1]
            if exemplars is not None and parsed[2] is not None:
                ex = parse_exemplar(parsed[2])
                if ex is not None:
                    exemplars[parsed[0]] = ex
    return out


def merge_prometheus_texts(texts):
    """Merge several exposition texts into one (the router's
    aggregated ``/metrics``): families are unioned (first HELP/TYPE
    seen wins), and samples with the IDENTICAL series key are SUMMED —
    engine-labeled serving families stay disjoint per engine, while
    process-level families (trace counters, watchdog totals) fold into
    fleet totals. Histogram buckets sum correctly because every
    input's buckets are already cumulative. OpenMetrics exemplars
    round-trip: per series key the largest-valued exemplar survives
    the merge (the fleet scrape keeps the worst retrievable trace per
    bucket, matching the registry's per-child rule). Output is
    deterministic: families sorted by name, samples sorted by key."""
    helps, types = {}, {}
    samples = {}
    exemplars = {}          # series key -> (value, raw_text)
    for text in texts:
        for line in text.splitlines():
            line = line.strip()
            if line.startswith("# HELP "):
                parts = line.split(None, 3)
                if len(parts) >= 3:
                    helps.setdefault(parts[2],
                                     parts[3] if len(parts) > 3 else "")
                continue
            if line.startswith("# TYPE "):
                parts = line.split(None, 3)
                if len(parts) >= 4:
                    types.setdefault(parts[2], parts[3])
                continue
            parsed = _parse_sample_line(line)
            if parsed is not None:
                samples[parsed[0]] = samples.get(parsed[0], 0.0) + parsed[1]
                if parsed[2] is not None:
                    ex = parse_exemplar(parsed[2])
                    prev = exemplars.get(parsed[0])
                    if ex is not None and (prev is None
                                           or ex["value"] >= prev[0]):
                        exemplars[parsed[0]] = (ex["value"], parsed[2])

    def family_of(key):
        name = key.split("{", 1)[0]
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = name[: -len(suffix)]
                if types.get(base) == "histogram":
                    return base
        return name

    by_family = {}
    for key in samples:
        by_family.setdefault(family_of(key), []).append(key)
    out = []
    for fam in sorted(set(by_family) | set(types)):
        if fam in helps and helps[fam]:
            out.append(f"# HELP {fam} {helps[fam]}")
        if fam in types:
            out.append(f"# TYPE {fam} {types[fam]}")
        for key in sorted(by_family.get(fam, ())):
            line = f"{key} {_fmt(samples[key])}"
            if key in exemplars:
                line += f" # {exemplars[key][1]}"
            out.append(line)
    return "\n".join(out) + "\n"


def parse_labels(key):
    """``name{a="x",b="y"}`` → ``(name, {"a": "x", "b": "y"})``
    (unescaping the spec's three label-value escapes)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    rest = rest.rstrip("}")
    labels = {}
    # split on commas outside quotes
    parts, buf, in_quote, prev = [], "", False, ""
    for ch in rest:
        if ch == '"' and prev != "\\":
            in_quote = not in_quote
        if ch == "," and not in_quote:
            parts.append(buf)
            buf = ""
        else:
            buf += ch
        prev = ch if not (ch == "\\" and prev == "\\") else ""
    if buf:
        parts.append(buf)
    for p in parts:
        k, _, v = p.partition("=")
        labels[k.strip()] = _unescape(v.strip().strip('"'))
    return name, labels


def _unescape(v):
    """Left-to-right unescape of the spec's three label-value escapes
    (a replace() chain would corrupt values mixing backslashes with
    'n' or quotes — '\\\\n' must decode to backslash+'n', not
    backslash+newline)."""
    out, i, n = [], 0, len(v)
    while i < n:
        ch = v[i]
        if ch == "\\" and i + 1 < n:
            nxt = v[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def histogram_quantile(parsed, name, q, match=None):
    """PromQL-style ``histogram_quantile`` over a parsed scrape:
    linear interpolation inside the bucket where the q-th sample
    falls. ``match`` filters by label subset (e.g. {"stage": "total"}).
    Returns None when the histogram has no samples. An estimate, not a
    sample percentile — good for cross-checking magnitudes, not for
    goldens."""
    match = match or {}
    buckets = []
    for key, val in parsed.items():
        n, labels = parse_labels(key)
        if n != f"{name}_bucket" or "le" not in labels:
            continue
        if any(labels.get(k) != str(v) for k, v in match.items()):
            continue
        le = labels["le"]
        buckets.append((float("inf") if le == "+Inf" else float(le), val))
    if not buckets:
        return None
    buckets.sort()
    total = buckets[-1][1]
    if total <= 0:
        return None
    rank = q / 100.0 * total
    lo_bound, lo_count = 0.0, 0.0
    for bound, cum in buckets:
        if cum >= rank:
            if bound == float("inf"):
                return lo_bound       # open-ended top bucket: its floor
            span = cum - lo_count
            frac = (rank - lo_count) / span if span else 1.0
            return lo_bound + (bound - lo_bound) * frac
        lo_bound, lo_count = bound, cum
    return buckets[-1][0]
