"""Alert egress: the delivery pipeline that takes a page OUT of the
process.

The SLO engine (:mod:`.slo` + :mod:`.alerts`) can judge the fleet and
walk a rule to *firing*, but until now the page never left the
process — an operator not already tailing ``/alerts`` learned nothing.
An :class:`AlertNotifier` consumes :class:`~.alerts.AlertDaemon` state
transitions (via ``AlertDaemon.add_listener``) and delivers them to
configured sinks:

- :class:`WebhookSink` — JSON POST to ``MXNET_TPU_ALERT_EGRESS_URL``
  (a pager bridge, Alertmanager, a chat webhook);
- :class:`FileSink` — JSONL append (tests, air-gapped runs);
- :class:`StdoutSink` — JSON lines on stdout.

Delivery discipline:

- **filtering** — only the transitions worth a human's attention ride
  out: by default ``firing`` and ``resolved`` of ``page``-severity
  rules (everything else counts ``skipped``);
- **fingerprinting + dedup** — each alert identity gets a stable
  fingerprint (``sha1(owner:alert)``); one firing episode delivers ONE
  page no matter how many times the daemon re-evaluates it, and the
  matching ``resolved`` clears the episode so a later re-fire pages
  again. The fingerprint rides the payload so a receiving pager can
  correlate fire/resolve pairs, and ``incident_id`` (from
  :mod:`.incidents`) ties the page to the correlated timeline;
- **retry with exponential backoff + jitter** — a sink failure retries
  ``MXNET_TPU_ALERT_EGRESS_RETRIES`` times, sleeping
  ``backoff * 2^attempt`` plus up to 50% jitter (thundering-herd
  hygiene when a whole fleet pages at once);
- **bounded on-disk dead-letter spool** — a notification that exhausts
  its retries is spooled to ``MXNET_TPU_ALERT_EGRESS_SPOOL`` (default
  under the flight-recorder dir) and REPLAYED on the next notifier
  start, so a page survives the death of the process that raised it;
  delivery deletes the spool file, so a replay delivers exactly once.

``mxnet_tpu_alert_egress_notifications_total{sink,result}`` accounts
every notification (delivered / retried-then-delivered counts as
delivered; failed / spooled / deduped / skipped / dropped), and
``mxnet_tpu_alert_egress_spool`` gauges the dead-letter depth.

``MXNET_TPU_ALERT_EGRESS=0`` — or no sink configured — means no
notifier: no thread, no families, zero cost (the daemon's listener
list stays empty).
"""
from __future__ import annotations

import hashlib
import json
import os
import random
import sys
import threading
import time
import urllib.request
from collections import OrderedDict, deque

from .. import envvars
from ..retrying import RetryPolicy
from . import events as _events
from .registry import REGISTRY

__all__ = ["Sink", "WebhookSink", "FileSink", "StdoutSink",
           "AlertNotifier", "default_notifier", "reset_default"]


class Sink:
    """One delivery target. ``send`` raises on failure — the notifier
    owns retries, backoff and the dead-letter spool."""

    name = "?"

    def send(self, payload):
        raise NotImplementedError


class WebhookSink(Sink):
    """JSON POST to a webhook URL; any non-2xx (or transport error)
    raises, i.e. retries."""

    name = "webhook"

    def __init__(self, url, timeout_s=5.0):
        self.url = str(url)
        self.timeout_s = float(timeout_s)

    def send(self, payload):
        data = json.dumps(payload, default=str).encode()
        req = urllib.request.Request(
            self.url, data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
            if not 200 <= r.status < 300:
                raise OSError(f"webhook answered HTTP {r.status}")


class FileSink(Sink):
    """JSONL append — one line per notification. Open-per-send keeps
    the sink valid across log rotation and lets a send fail loudly
    (unwritable path) instead of buffering into the void."""

    name = "file"

    def __init__(self, path):
        self.path = str(path)

    def send(self, payload):
        with open(self.path, "a") as f:
            f.write(json.dumps(payload, default=str) + "\n")


class StdoutSink(Sink):
    name = "stdout"

    def send(self, payload):
        sys.stdout.write(json.dumps(payload, default=str) + "\n")
        sys.stdout.flush()


def fingerprint(owner, alert):
    """Stable identity of one alert rule across its whole lifecycle —
    the key firing/resolved notifications correlate on."""
    return hashlib.sha1(f"{owner}:{alert}".encode()).hexdigest()[:12]


class AlertNotifier:
    """Background delivery worker over a set of sinks.

    ``notify(transition_record)`` is the producer surface (attach it
    with ``daemon.add_listener(notifier.notify)``): filter → dedup →
    enqueue; the worker thread delivers with per-sink retry/backoff
    and spools exhausted notifications. ``sleep``/``rng`` are
    injectable so the retry/backoff golden runs on a scripted clock;
    :meth:`process_pending` drains the queue on the caller's thread
    for thread-free tests.
    """

    def __init__(self, sinks=None, retries=None, backoff_s=None,
                 spool_dir=None, spool_max=None,
                 states=("firing", "resolved"), severities=("page",),
                 registry=None, sleep=None, rng=None):
        reg = registry if registry is not None else REGISTRY
        self.sinks = list(sinks or [])
        self.retries = (int(retries) if retries is not None
                        else envvars.get("MXNET_TPU_ALERT_EGRESS_RETRIES"))
        self.backoff_s = (float(backoff_s) if backoff_s is not None
                          else envvars.get(
                              "MXNET_TPU_ALERT_EGRESS_BACKOFF_S"))
        self.spool_dir = spool_dir or self._default_spool()
        self.spool_max = (int(spool_max) if spool_max is not None
                          else envvars.get(
                              "MXNET_TPU_ALERT_EGRESS_SPOOL_MAX"))
        self.states = tuple(states)
        self.severities = tuple(severities)
        self._sleep = sleep if sleep is not None else time.sleep
        self._rng = rng if rng is not None else random.Random()
        # the one repo-wide retry shape (mxnet_tpu.retrying): doubling
        # backoff from backoff_s with up to 50% proportional jitter,
        # retries RE-tries = retries+1 attempts — injectable sleep/rng
        # keep the scripted-clock goldens exact
        self._policy = RetryPolicy(retries=self.retries,
                                   backoff_s=self.backoff_s,
                                   multiplier=2.0, jitter=0.5,
                                   sleep=self._sleep, rng=self._rng)
        self._dq = deque()
        self._cv = threading.Condition()
        self._idle = True
        self._thread = None
        self._closed = False
        # fingerprint -> transition state already delivered this
        # episode (bounded; resolved clears firing so a re-fire pages)
        self._delivered = OrderedDict()
        self._delivered_cap = 512
        self._seq = 0
        self._c_note = reg.counter(
            "mxnet_tpu_alert_egress_notifications_total",
            "alert notifications by sink and result (delivered / "
            "failed / spooled / deduped / skipped / dropped)",
            ("sink", "result"))
        self._c_retries = reg.counter(
            "mxnet_tpu_alert_egress_retries_total",
            "delivery retries, per sink", ("sink",))
        self._g_spool = reg.gauge(
            "mxnet_tpu_alert_egress_spool",
            "dead-letter spool depth (undelivered notification files)")
        self._g_spool.set_function(self._spool_depth)

    @staticmethod
    def _default_spool():
        explicit = envvars.get("MXNET_TPU_ALERT_EGRESS_SPOOL")
        if explicit:
            return explicit
        flight = (envvars.get("MXNET_TPU_FLIGHT_DIR")
                  or os.path.join(os.getcwd(), "mxnet_tpu_flight"))
        return os.path.join(flight, "egress-spool")

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        """Start the delivery thread and enqueue the spool replay."""
        with self._cv:
            if self._thread is not None or self._closed:
                return self
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="mxnet_tpu_alert_egress")
            self._thread.start()
        self.replay_spool()
        return self

    def stop(self, timeout=5.0):
        with self._cv:
            self._closed = True
            t, self._thread = self._thread, None
            self._cv.notify_all()
        if t is not None:
            t.join(timeout=timeout)

    def flush(self, timeout=10.0):
        """Block until the queue is drained and the worker idle (or
        timeout). Returns True when fully drained."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._dq or not self._idle:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(0.05, remaining))
        return True

    # -- producer ----------------------------------------------------------
    def notify(self, rec):
        """Consume one alert-daemon transition record. Filters to the
        configured states/severities, dedupes per firing episode, and
        enqueues the rest for delivery. Never raises (it runs on the
        alert daemon's evaluation thread)."""
        try:
            to = rec.get("to")
            if to not in self.states \
                    or rec.get("severity") not in self.severities:
                self._count("-", "skipped")
                return None
            fp = fingerprint(rec.get("owner"), rec.get("alert"))
            key = f"{fp}:{to}"
            with self._cv:
                if key in self._delivered:
                    dup = True
                else:
                    dup = False
                    self._delivered[key] = True
                    # the opposite transition opens a fresh episode: a
                    # resolve clears the firing key so a later re-fire
                    # pages again (and vice versa) — flapping pages per
                    # episode, never per evaluation
                    other = "resolved" if to == "firing" else "firing"
                    self._delivered.pop(f"{fp}:{other}", None)
                    while len(self._delivered) > self._delivered_cap:
                        self._delivered.popitem(last=False)
            if dup:
                self._count("-", "deduped")
                return None
            note = dict(rec, fingerprint=fp, pid=os.getpid())
            try:
                from . import incidents as _incidents
                iid = _incidents.id_for_alert(rec.get("owner"),
                                              rec.get("alert"))
                if iid is not None:
                    note["incident_id"] = iid
            except Exception:
                pass
            self._enqueue(note)
            return note
        except Exception as e:
            _events.emit("alert_egress_error", error=repr(e))
            return None

    def _enqueue(self, note):
        with self._cv:
            if self._closed:
                return
            self._dq.append(note)
            self._cv.notify()

    # -- worker ------------------------------------------------------------
    def _run(self):
        while True:
            with self._cv:
                self._idle = True
                self._cv.notify_all()
                while not self._dq and not self._closed:
                    self._cv.wait(0.5)
                if self._closed and not self._dq:
                    return
                note = self._dq.popleft()
                self._idle = False
            self._deliver(note)

    def process_pending(self):
        """Deliver everything queued on the CALLER's thread (tests and
        scripted-clock goldens — no worker thread required). Returns
        the number of notifications processed."""
        n = 0
        while True:
            with self._cv:
                if not self._dq:
                    return n
                note = self._dq.popleft()
            self._deliver(note)
            n += 1

    def _deliver(self, note):
        # spool-replayed notes carry their target sink; live notes go
        # to every configured sink independently
        only = note.pop("_sink", None)
        for sink in self.sinks:
            if only is not None and sink.name != only:
                continue
            if self._deliver_to(sink, note):
                self._count(sink.name, "delivered")
            else:
                self._count(sink.name, "failed")
                self._spool(sink, note)

    def _deliver_to(self, sink, note):
        def _on_retry(_attempt, _exc):
            self._c_retries.labels(sink=sink.name).inc()

        try:
            self._policy.call(lambda: sink.send(note),
                              on_retry=_on_retry)
            return True
        except Exception as e:
            _events.emit("alert_egress_failed", sink=sink.name,
                         alert=note.get("alert"), error=repr(e))
            return False

    # -- dead-letter spool --------------------------------------------------
    def _spool_depth(self):
        try:
            return len([n for n in os.listdir(self.spool_dir)
                        if n.endswith(".json")])
        except OSError:
            return 0

    def _spool(self, sink, note):
        """Persist one undeliverable notification (bounded: past
        ``spool_max`` the OLDEST entry is dropped so the newest pages
        survive). Never raises — the spool is the last resort, not a
        new failure mode."""
        try:
            os.makedirs(self.spool_dir, exist_ok=True)
            existing = sorted(n for n in os.listdir(self.spool_dir)
                              if n.endswith(".json"))
            while len(existing) >= max(1, self.spool_max):
                victim = existing.pop(0)
                try:
                    os.remove(os.path.join(self.spool_dir, victim))
                except OSError:
                    pass
                self._count(sink.name, "dropped")
            with self._cv:
                self._seq += 1
                seq = self._seq
            name = (f"{time.time():.3f}-{os.getpid()}-{seq}-"
                    f"{sink.name}.json")
            tmp = os.path.join(self.spool_dir, name + ".tmp")
            with open(tmp, "w") as f:
                json.dump(dict(note, _sink=sink.name), f, default=str)
            os.replace(tmp, os.path.join(self.spool_dir, name))
            self._count(sink.name, "spooled")
            _events.emit("alert_egress_spooled", sink=sink.name,
                         alert=note.get("alert"))
        except Exception as e:
            _events.emit("alert_egress_error", error=repr(e))

    def replay_spool(self):
        """Re-enqueue every spooled notification (oldest first) and
        delete the files — a delivered replay therefore delivers
        exactly once; a replay that fails again simply re-spools."""
        try:
            names = sorted(n for n in os.listdir(self.spool_dir)
                           if n.endswith(".json"))
        except OSError:
            return 0
        replayed = 0
        for name in names:
            path = os.path.join(self.spool_dir, name)
            try:
                with open(path) as f:
                    note = json.load(f)
                os.remove(path)
            except (OSError, ValueError):
                continue
            note["replayed"] = True
            self._enqueue(note)
            replayed += 1
        if replayed:
            _events.emit("alert_egress_replay", count=replayed)
        return replayed

    def _count(self, sink, result):
        self._c_note.labels(sink=sink, result=result).inc()


# -- process singleton (env-configured) -------------------------------------

_default = None
_default_lock = threading.Lock()
_default_built = False


def default_notifier():
    """The process-wide env-configured notifier, built (and started)
    on first call — or None when ``MXNET_TPU_ALERT_EGRESS=0`` or no
    sink is configured (then nothing is registered and no thread
    runs). Every :class:`~.alerts.AlertDaemon` attaches this on
    ``start()`` so one delivery pipeline serves all owners; the
    fingerprint dedup keeps N daemons from double-paging."""
    global _default, _default_built
    with _default_lock:
        if _default_built:
            return _default
        _default_built = True
        if not envvars.get("MXNET_TPU_ALERT_EGRESS"):
            return None
        sinks = []
        url = envvars.get("MXNET_TPU_ALERT_EGRESS_URL")
        if url:
            sinks.append(WebhookSink(url))
        path = envvars.get("MXNET_TPU_ALERT_EGRESS_FILE")
        if path:
            sinks.append(FileSink(path))
        if envvars.get("MXNET_TPU_ALERT_EGRESS_STDOUT"):
            sinks.append(StdoutSink())
        if not sinks:
            return None
        _default = AlertNotifier(sinks=sinks).start()
        return _default


def reset_default():
    """Tests only: stop and forget the process notifier so the next
    ``default_notifier()`` re-reads the environment."""
    global _default, _default_built
    with _default_lock:
        n, _default = _default, None
        _default_built = False
    if n is not None:
        n.stop()
