"""Structured JSONL run-event log.

One line per lifecycle event — engine start/stop/abort, per-shape
compile begin/end, request shed/expiry, wire-frame refusal, kvstore
optimizer updates — so a run leaves a machine-readable record next to
the human stderr stream. Every record carries::

    {"ts": <wall unix s>, "mono": <monotonic s>, "pid": <pid>,
     "event": <type>, "trace_id": <active trace id or null>, ...fields}

Wall time orders events across machines; the monotonic stamp orders
them exactly within a process (wall clocks step, monotonic doesn't).

Cost discipline: when no log is configured, :func:`emit` is one global
read + None check — the instrumented hot paths pay nothing (guarded by
the disabled-path microbenchmark in tests/test_telemetry.py).

Configuration: :func:`configure` in code, or the
``MXNET_TPU_EVENT_LOG`` env var (read once, on first emit). If the
value names a DIRECTORY, each process writes its own
``events-<pid>.jsonl`` inside it — exactly what a multi-process
dist_async launch needs (one env var in the launcher, one log per
process, no interleaved writes).
"""
from __future__ import annotations

import json
import os
import threading
import time

from .trace import current_trace_id

__all__ = ["EventLog", "configure", "emit", "get_log", "read_events"]


class EventLog:
    """Append-only JSONL writer (thread-safe, line-buffered: every
    event is durable on its own ``write`` — a crashed process keeps
    its log up to the last event)."""

    def __init__(self, path, component=None):
        self.path = str(path)
        self.component = component
        self._lock = threading.Lock()
        self._f = open(self.path, "a", buffering=1)

    def emit(self, event, **fields):
        rec = {"ts": round(time.time(), 6),
               "mono": round(time.monotonic(), 6),
               "pid": os.getpid(),
               "event": event,
               "trace_id": fields.pop("trace_id", None)
               or current_trace_id()}
        if self.component:
            rec["component"] = self.component
        rec.update(fields)
        line = json.dumps(rec, default=str)
        with self._lock:
            try:
                self._f.write(line + "\n")
            except (ValueError, OSError):
                # a concurrent configure()/close() or a full disk must
                # never take an instrumented hot path down — telemetry
                # loses one line, the serving batch survives
                pass

    def close(self):
        with self._lock:
            try:
                self._f.close()
            except OSError:
                pass


_global = None
_env_checked = False
_lock = threading.Lock()


def _resolve_path(value):
    if os.path.isdir(value):
        return os.path.join(value, f"events-{os.getpid()}.jsonl")
    return value


def configure(path=None, component=None):
    """Install (or with ``path=None`` remove) the process event log.
    Returns the :class:`EventLog` (or None)."""
    global _global, _env_checked
    with _lock:
        if _global is not None:
            _global.close()
        _global = (EventLog(_resolve_path(path), component)
                   if path is not None else None)
        _env_checked = True          # explicit config outranks the env
    return _global


def get_log():
    """The active process log, auto-configuring from
    ``MXNET_TPU_EVENT_LOG`` on first call. None when logging is off."""
    global _global, _env_checked
    if _global is None and not _env_checked:
        with _lock:
            if _global is None and not _env_checked:
                env = os.environ.get("MXNET_TPU_EVENT_LOG")
                if env:
                    try:
                        _global = EventLog(_resolve_path(env))
                    except OSError:
                        _global = None
                _env_checked = True
    return _global


def emit(event, **fields):
    """Emit to the process log; a no-op (one None check after the
    first call) when no log is configured."""
    log = get_log()
    if log is not None:
        log.emit(event, **fields)


def read_events(path, event=None):
    """Parse an events JSONL file (tolerating a torn final line from a
    live writer); optionally filter by event type."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if event is None or rec.get("event") == event:
                out.append(rec)
    return out
