"""Structured JSONL run-event log.

One line per lifecycle event — engine start/stop/abort, per-shape
compile begin/end, request shed/expiry, wire-frame refusal, kvstore
optimizer updates, watchdog anomalies — so a run leaves a
machine-readable record next to the human stderr stream. Every record
carries::

    {"ts": <wall unix s>, "mono": <monotonic s>, "pid": <pid>,
     "event": <type>, "trace_id": <active trace id or null>, ...fields}

Wall time orders events across machines; the monotonic stamp orders
them exactly within a process (wall clocks step, monotonic doesn't).

Cost discipline: when no log is configured and no tap is attached,
:func:`emit` is one global read + two truthiness checks — the
instrumented hot paths pay nothing (guarded by the disabled-path
microbenchmark in tests/test_telemetry.py).

Configuration: :func:`configure` in code, or the
``MXNET_TPU_EVENT_LOG`` env var (read once, on first emit). If the
value names a DIRECTORY, each process writes its own
``events-<pid>.jsonl`` inside it — exactly what a multi-process
dist_async launch needs (one env var in the launcher, one log per
process, no interleaved writes).

Rotation: a long-lived server's JSONL must not grow unbounded. Set
``MXNET_TPU_EVENT_LOG_MAX_MB`` (or pass ``max_bytes``) and the log
rotates in place once it crosses the cap — ``events.jsonl`` becomes
``events.jsonl.1`` (older shift to ``.2``, ``.3``, …), bounded by
``MXNET_TPU_EVENT_LOG_KEEP`` rotated files (default 3; the oldest is
deleted). Rotation happens under the writer lock (thread-safe reopen);
:func:`read_events` transparently reads across all rotations, oldest
first.

Taps: the flight recorder (:mod:`.recorder`) registers an in-memory
tap via :func:`add_tap` so the last N events are available in a crash
bundle even when no file log is configured.
"""
from __future__ import annotations

import json
import os
import threading
import time

from .. import envvars
from .trace import current_trace_id

__all__ = ["EventLog", "configure", "emit", "get_log", "read_events",
           "add_tap", "remove_tap"]

_ROTATE_SCAN_MAX = 64      # read_events looks this far for .N siblings


def _make_record(event, fields, component=None):
    rec = {"ts": round(time.time(), 6),
           "mono": round(time.monotonic(), 6),
           "pid": os.getpid(),
           "event": event,
           "trace_id": fields.pop("trace_id", None)
           or current_trace_id()}
    if component:
        rec["component"] = component
    rec.update(fields)
    return rec


class EventLog:
    """Append-only JSONL writer (thread-safe, line-buffered: every
    event is durable on its own ``write`` — a crashed process keeps
    its log up to the last event). Rotates in place at ``max_bytes``
    keeping ``keep`` older files."""

    def __init__(self, path, component=None, max_bytes=None, keep=None):
        self.path = str(path)
        self.component = component
        if max_bytes is None:
            mb = envvars.get("MXNET_TPU_EVENT_LOG_MAX_MB")
            max_bytes = int(mb * 1024 * 1024) if mb else None
        self.max_bytes = max_bytes
        self.keep = (int(keep) if keep is not None
                     else envvars.get("MXNET_TPU_EVENT_LOG_KEEP"))
        self._lock = threading.Lock()
        self._f = open(self.path, "a", buffering=1)
        try:
            self._size = os.path.getsize(self.path)
        except OSError:
            self._size = 0

    def emit(self, event, **fields):
        self.write_record(_make_record(event, fields, self.component))

    def write_record(self, rec):
        """Serialize + append one already-built record (the module
        :func:`emit` builds the record once and shares it with the
        flight-recorder taps)."""
        line = json.dumps(rec, default=str)
        with self._lock:
            try:
                if self._f is None:
                    # a failed rotation reopen left the log dark; keep
                    # trying — the transient (fd pressure, a replaced
                    # directory) may have cleared
                    self._reopen_locked()
                self._f.write(line + "\n")
                self._size += len(line) + 1
                if self.max_bytes and self._size >= self.max_bytes:
                    self._rotate_locked()
            except (ValueError, OSError):
                # a concurrent configure()/close() or a full disk must
                # never take an instrumented hot path down — telemetry
                # loses one line, the serving batch survives
                pass

    def _reopen_locked(self):
        self._f = open(self.path, "a", buffering=1)
        try:
            self._size = os.path.getsize(self.path)
        except OSError:
            self._size = 0

    def _rotate_locked(self):
        """Shift path → path.1 → … → path.keep (oldest dropped) and
        reopen; called with the writer lock held so no line is torn
        across the swap."""
        try:
            self._f.close()
        except OSError:
            pass
        self._f = None
        try:
            if self.keep >= 1:
                oldest = f"{self.path}.{self.keep}"
                if os.path.exists(oldest):
                    os.remove(oldest)
                for i in range(self.keep - 1, 0, -1):
                    src = f"{self.path}.{i}"
                    if os.path.exists(src):
                        os.replace(src, f"{self.path}.{i + 1}")
                os.replace(self.path, f"{self.path}.1")
            else:
                # keep=0: rotate-without-retention still enforces the
                # size cap — truncate the live file in place
                os.remove(self.path)
        except OSError:
            pass            # rotation failure must not kill the writer
        # reopen failure leaves _f None; write_record retries per emit
        self._reopen_locked()

    def close(self):
        with self._lock:
            try:
                if self._f is not None:
                    self._f.close()
            except OSError:
                pass


_global = None
_env_checked = False
_lock = threading.Lock()
_taps = []


def _resolve_path(value):
    if os.path.isdir(value):
        return os.path.join(value, f"events-{os.getpid()}.jsonl")
    return value


def configure(path=None, component=None, max_bytes=None, keep=None):
    """Install (or with ``path=None`` remove) the process event log.
    Returns the :class:`EventLog` (or None)."""
    global _global, _env_checked
    with _lock:
        if _global is not None:
            _global.close()
        _global = (EventLog(_resolve_path(path), component,
                            max_bytes=max_bytes, keep=keep)
                   if path is not None else None)
        _env_checked = True          # explicit config outranks the env
    return _global


def get_log():
    """The active process log, auto-configuring from
    ``MXNET_TPU_EVENT_LOG`` on first call. None when logging is off."""
    global _global, _env_checked
    if _global is None and not _env_checked:
        with _lock:
            if _global is None and not _env_checked:
                env = envvars.get("MXNET_TPU_EVENT_LOG")
                if env:
                    try:
                        _global = EventLog(_resolve_path(env))
                    except OSError:
                        _global = None
                _env_checked = True
    return _global


def add_tap(fn):
    """Register ``fn(record_dict)`` called on every emitted event
    (flight-recorder ring). Taps run even with no file log."""
    if fn not in _taps:
        _taps.append(fn)


def remove_tap(fn):
    try:
        _taps.remove(fn)
    except ValueError:
        pass


def emit(event, **fields):
    """Emit to the process log + any taps; a no-op (one None check and
    one truthiness check after the first call) when neither is
    attached. The record is built ONCE and shared — taps (the
    flight-recorder ring) see the same timestamps and component tag
    the on-disk log carries."""
    log = get_log()
    if log is None and not _taps:
        return
    rec = _make_record(event, fields,
                       log.component if log is not None else None)
    for tap in list(_taps):
        try:
            tap(rec)
        except Exception:
            pass
    if log is not None:
        log.write_record(rec)


def read_events(path, event=None, skipped=None):
    """Parse an events JSONL file — including its rotated ``.N``
    siblings, oldest first — tolerating torn lines from a live writer
    or a hard kill; optionally filter by event type.

    A process killed mid-``write`` leaves a truncated final line —
    possibly cut inside a multi-byte UTF-8 sequence, which a strict
    decode would raise on MID-POSTMORTEM. Unparseable lines are
    skipped and counted instead: pass a dict as ``skipped`` to get
    per-file skip counts back (only files with skips appear)."""
    rotated = []
    for i in range(1, _ROTATE_SCAN_MAX + 1):
        p = f"{path}.{i}"
        if os.path.exists(p):
            rotated.append(p)
    paths = list(reversed(rotated))          # highest .N = oldest
    if os.path.exists(path) or not paths:
        paths.append(str(path))
    out = []
    for p in paths:
        # errors="replace": a line torn inside a multi-byte sequence
        # must land in the json.loads skip path, not raise on decode
        with open(p, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    if skipped is not None:
                        skipped[p] = skipped.get(p, 0) + 1
                    continue
                if not isinstance(rec, dict):
                    if skipped is not None:
                        skipped[p] = skipped.get(p, 0) + 1
                    continue
                if event is None or rec.get("event") == event:
                    out.append(rec)
    return out
