"""Host + device resource accounting: what this process costs to run.

The serving/metrics stack answers *how fast*; this module answers *how
big*: host RSS, open file descriptors, live threads (all read off
``/proc/self`` — stdlib-only, graceful zeros off Linux), and device
memory (PJRT ``memory_stats()`` where the backend provides them, the
byte total of live ``jax.Array``\\ s as the framework-tracked fallback
— graceful zeros on backends with neither).

Two consumption paths:

- **gauges** on the process registry
  (``mxnet_tpu_resource_rss_bytes`` etc.), refreshed by
  :func:`sample` — the continuous-profiler daemon
  (:mod:`.profiling`) calls it every ``MXNET_TPU_PROF_RESOURCE_S``
  seconds, so a ``/metrics`` scrape of any serving process carries
  its resource footprint without extra wiring;
- **watermarks**: :func:`sample` also folds each reading into
  process-lifetime peaks (``rss_peak_bytes`` / ``device_peak_bytes``)
  — the per-leg bench records carry them so a memory regression shows
  up in ``bench_suite_summary``, not just in an OOM three legs later.

Everything here must stay cheap enough to run every second forever: a
few ``/proc`` reads and one pass over live device arrays.
"""
from __future__ import annotations

import os
import threading

from .registry import REGISTRY

__all__ = ["snapshot", "sample", "watermarks", "reset_watermarks",
           "compact"]

_lock = threading.Lock()
_peaks = {"rss_peak_bytes": 0, "device_peak_bytes": 0}

_g_rss = REGISTRY.gauge(
    "mxnet_tpu_resource_rss_bytes",
    "host resident-set size of this process (from /proc/self/statm)")
_g_fds = REGISTRY.gauge(
    "mxnet_tpu_resource_open_fds",
    "open file descriptors of this process")
_g_threads = REGISTRY.gauge(
    "mxnet_tpu_resource_threads",
    "live Python threads in this process")
_g_dev = REGISTRY.gauge(
    "mxnet_tpu_resource_device_bytes_in_use",
    "device bytes in use per PJRT memory_stats (0 when the backend "
    "reports none, e.g. CPU)")
_g_live = REGISTRY.gauge(
    "mxnet_tpu_resource_live_buffer_bytes",
    "byte total of live jax.Array buffers (framework-tracked "
    "allocations; the CPU-visible device-memory proxy)")
_g_rss_peak = REGISTRY.gauge(
    "mxnet_tpu_resource_rss_peak_bytes",
    "process-lifetime peak of mxnet_tpu_resource_rss_bytes as sampled")
_g_dev_peak = REGISTRY.gauge(
    "mxnet_tpu_resource_device_peak_bytes",
    "process-lifetime peak of max(device bytes in use, live buffer "
    "bytes) as sampled")

_page_size = None


def _pagesize():
    global _page_size
    if _page_size is None:
        try:
            _page_size = os.sysconf("SC_PAGE_SIZE")
        except (ValueError, OSError, AttributeError):
            _page_size = 4096
    return _page_size


def rss_bytes():
    """Resident-set bytes from ``/proc/self/statm`` (0 off Linux)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _pagesize()
    except (OSError, ValueError, IndexError):
        return 0


def open_fds():
    """Open fd count from ``/proc/self/fd`` (0 off Linux)."""
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def thread_count():
    return threading.active_count()


def device_memory():
    """``(bytes_in_use, live_buffer_bytes)`` — PJRT memory stats plus
    the live-array byte total; each gracefully 0 when unavailable."""
    in_use = live = 0
    try:
        import jax
    except Exception:
        return 0, 0
    try:
        stats = jax.devices()[0].memory_stats()
        if stats:
            in_use = int(stats.get("bytes_in_use", 0))
    except Exception:
        in_use = 0
    try:
        live = int(sum(a.nbytes for a in jax.live_arrays()))
    except Exception:
        live = 0
    return in_use, live


def snapshot():
    """One reading of every resource signal (no gauge/watermark side
    effects — :func:`sample` is the mutating sweep)."""
    in_use, live = device_memory()
    return {"rss_bytes": rss_bytes(),
            "open_fds": open_fds(),
            "threads": thread_count(),
            "device_bytes_in_use": in_use,
            "live_buffer_bytes": live}


def sample():
    """Take one reading, refresh the registry gauges, fold the
    watermarks, and return the snapshot dict (with peaks included).
    This is what the profiler daemon runs every
    ``MXNET_TPU_PROF_RESOURCE_S`` seconds."""
    snap = snapshot()
    dev = max(snap["device_bytes_in_use"], snap["live_buffer_bytes"])
    with _lock:
        if snap["rss_bytes"] > _peaks["rss_peak_bytes"]:
            _peaks["rss_peak_bytes"] = snap["rss_bytes"]
        if dev > _peaks["device_peak_bytes"]:
            _peaks["device_peak_bytes"] = dev
        peaks = dict(_peaks)
    _g_rss.set(snap["rss_bytes"])
    _g_fds.set(snap["open_fds"])
    _g_threads.set(snap["threads"])
    _g_dev.set(snap["device_bytes_in_use"])
    _g_live.set(snap["live_buffer_bytes"])
    _g_rss_peak.set(peaks["rss_peak_bytes"])
    _g_dev_peak.set(peaks["device_peak_bytes"])
    snap.update(peaks)
    return snap


def watermarks():
    """Process-lifetime peaks over every :func:`sample` so far."""
    with _lock:
        return dict(_peaks)


def reset_watermarks():
    """Start a fresh watermark window (a bench leg measuring only its
    own footprint)."""
    with _lock:
        _peaks["rss_peak_bytes"] = 0
        _peaks["device_peak_bytes"] = 0


def compact():
    """Rounded-MB view for bench records (one fresh sample folded in,
    so a leg that never ran the daemon still reports real numbers)."""
    snap = sample()
    mb = 1024.0 * 1024.0
    return {"rss_mb": round(snap["rss_bytes"] / mb, 1),
            "rss_peak_mb": round(snap["rss_peak_bytes"] / mb, 1),
            "device_mem_mb": round(
                max(snap["device_bytes_in_use"],
                    snap["live_buffer_bytes"]) / mb, 1),
            "device_peak_mb": round(snap["device_peak_bytes"] / mb, 1),
            "open_fds": snap["open_fds"],
            "threads": snap["threads"]}
