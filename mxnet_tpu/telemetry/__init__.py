"""Framework-wide telemetry (`mxnet_tpu.telemetry`).

The first CROSS-PROCESS observability layer of the stack (reference
lineage: MXNet Model Server's management-API metrics + MXNet
src/profiler/'s chrome://tracing feed, which this repo's in-process
``ServingStats``/``profiler.py`` reproduce — scrapeable from outside
the process starting here). Four pieces:

- :mod:`.registry` — process-wide thread-safe Counter/Gauge/Histogram
  families with label sets (module-level :data:`REGISTRY` default);
- :mod:`.expo` — stdlib-http background server: Prometheus
  ``/metrics``, ``/healthz`` liveness, ``/stats`` JSON;
- :mod:`.events` — structured JSONL run-event log (wall + monotonic
  stamps, pid, event type, trace id), env-configured via
  ``MXNET_TPU_EVENT_LOG``;
- :mod:`.trace` — trace-id propagation: minted at
  ``ServingEngine.submit``, rides a contextvar into profiler spans,
  and crosses the dist_async wire so both processes' event logs
  correlate on the same push;
- :mod:`.spans` — hierarchical spans over those trace ids (Dapper
  lineage): a bounded ring of tail-sampled traces (slow/errored/shed
  kept in full, the rest counted and dropped), served at ``/traces``
  + ``/traces/<id>`` and merged into ``profiler.dump()``'s
  Chrome-trace stream;
- :mod:`.recorder` — flight recorder + stall watchdog: recent-event
  ring, post-mortem bundles (spans + registry snapshot + all-thread
  stacks) on watchdog trip / crash / SIGUSR2;
- :mod:`.profiling` — always-on continuous sampling profiler
  (GWP lineage): one daemon folding every thread's stack into
  bounded collapsed-stack counts at ``MXNET_TPU_PROF_HZ``, served at
  ``/profile`` and dumped as ``profile.txt`` in flight bundles;
- :mod:`.resources` — host RSS/fd/thread + device-memory gauges and
  process-lifetime watermarks, swept by the profiler daemon;
- :mod:`.slo` + :mod:`.alerts` — the judging layer: a declarative SLO
  registry (latency quantiles, availability, cost budgets, gauge
  bounds) evaluated by an in-process alert daemon — SRE-workbook
  multi-window multi-burn-rate rules, threshold and absence rules,
  pending→firing→resolved state machine, ``/slo`` + ``/alerts``
  endpoints, and OpenMetrics histogram exemplars linking a firing
  latency alert to retrievable traces at ``/traces/<id>``;
- :mod:`.canary` — black-box synthetic monitoring: a router-side
  prober submits golden requests to every seat from OUTSIDE (binary
  wire + HTTP, round-robined), checks response checksums, and feeds
  per-seat canary-absence page rules — a wedged engine pages even
  while its own ``/healthz`` answers green;
- :mod:`.egress` — alert delivery out of the process: webhook/file/
  stdout sinks with retry + exponential backoff, fingerprint dedup
  and a bounded on-disk dead-letter spool replayed on restart;
- :mod:`.incidents` — the correlated incident timeline: alert
  firings, watchdog trips, scoreboard transitions, restarts and
  flight bundles fold into one incident object per outage, served at
  ``/incidents`` and stamped (incident id) into flight bundles.

Quickstart::

    from mxnet_tpu import telemetry

    srv = engine.expose(port=9100)        # ServingEngine exposition
    # curl :9100/metrics | :9100/healthz | :9100/stats | :9100/traces

    telemetry.events.configure("run-events.jsonl")
    c = telemetry.REGISTRY.counter("my_total", "things", ("kind",))
    c.labels(kind="good").inc()

    with telemetry.span("my/stage", shard=3):   # nested spans
        ...
"""
from . import (alerts, canary, egress, events, expo, incidents,
               profiling, recorder, resources, slo, spans, trace)
from .events import EventLog
from .expo import (TelemetryServer, histogram_quantile, parse_exemplar,
                   parse_prometheus_text, start_server)
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       REGISTRY, DEFAULT_MS_BUCKETS)
from .spans import (Span, current_span, current_span_id, get_trace,
                    record_span, span, start_span, traces_summary,
                    use_span)
from .trace import (current_trace_id, new_trace_id, set_trace_id,
                    trace_context)

__all__ = ["REGISTRY", "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "DEFAULT_MS_BUCKETS", "TelemetryServer", "start_server",
           "parse_prometheus_text", "parse_exemplar",
           "histogram_quantile", "EventLog",
           "events", "expo", "trace", "spans", "recorder", "profiling",
           "resources", "slo", "alerts", "canary", "egress", "incidents",
           "new_trace_id", "current_trace_id", "set_trace_id",
           "trace_context", "Span", "span", "start_span", "record_span",
           "use_span", "current_span", "current_span_id",
           "traces_summary", "get_trace"]
