"""Framework-wide telemetry (`mxnet_tpu.telemetry`).

The first CROSS-PROCESS observability layer of the stack (reference
lineage: MXNet Model Server's management-API metrics + MXNet
src/profiler/'s chrome://tracing feed, which this repo's in-process
``ServingStats``/``profiler.py`` reproduce — scrapeable from outside
the process starting here). Four pieces:

- :mod:`.registry` — process-wide thread-safe Counter/Gauge/Histogram
  families with label sets (module-level :data:`REGISTRY` default);
- :mod:`.expo` — stdlib-http background server: Prometheus
  ``/metrics``, ``/healthz`` liveness, ``/stats`` JSON;
- :mod:`.events` — structured JSONL run-event log (wall + monotonic
  stamps, pid, event type, trace id), env-configured via
  ``MXNET_TPU_EVENT_LOG``;
- :mod:`.trace` — trace-id propagation: minted at
  ``ServingEngine.submit``, rides a contextvar into profiler spans,
  and crosses the dist_async wire so both processes' event logs
  correlate on the same push.

Quickstart::

    from mxnet_tpu import telemetry

    srv = engine.expose(port=9100)        # ServingEngine exposition
    # curl :9100/metrics | :9100/healthz | :9100/stats

    telemetry.events.configure("run-events.jsonl")
    c = telemetry.REGISTRY.counter("my_total", "things", ("kind",))
    c.labels(kind="good").inc()
"""
from . import events, expo, trace
from .events import EventLog
from .expo import (TelemetryServer, histogram_quantile,
                   parse_prometheus_text, start_server)
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       REGISTRY, DEFAULT_MS_BUCKETS)
from .trace import (current_trace_id, new_trace_id, set_trace_id,
                    trace_context)

__all__ = ["REGISTRY", "MetricsRegistry", "Counter", "Gauge", "Histogram",
           "DEFAULT_MS_BUCKETS", "TelemetryServer", "start_server",
           "parse_prometheus_text", "histogram_quantile", "EventLog",
           "events", "expo", "trace", "new_trace_id", "current_trace_id",
           "set_trace_id", "trace_context"]
