"""Black-box synthetic canary probing: don't trust the seat's word.

Every health signal the fleet had so far is WHITE-BOX and
self-reported: an engine whose worker loop wedges mid-forward still
answers a green ``/healthz`` (the handler thread is fine, the worker
thread is merely stuck), and the router scoreboard folds exactly those
self-reports. A :class:`CanaryProber` closes that gap the way
production fleets do — by serving the product path from outside:

- a daemon on the ROUTER side submits one synthetic **golden request**
  per seat per round, over the real dispatch transports — the binary
  wire and the HTTP ``/submit`` path, round-robined per seat so each
  transport stays continuously exercised (in-process seats without an
  exposition endpoint are driven through ``engine.submit`` directly,
  transport ``local``);
- the response CONTENT is checked against a per-model **golden
  checksum** (established on the first successful probe, or pinned via
  ``golden=``): a seat that answers quickly but wrongly — stale
  weights after a botched hot-swap, a corrupted cache — counts
  ``checksum_mismatch``, not ``ok``;
- outcomes and latency land in ``mxnet_tpu_canary_*`` families, every
  one tagged ``traffic="synthetic"`` so loadgen's client-vs-ledger
  cost reconciliation (and any dashboard) can exclude canary traffic;
  the amortized bill a successful probe carries back feeds
  ``mxnet_tpu_canary_billed_*`` — exactly what the reconciliation
  subtracts;
- the paging signal is an **absence rule** per seat on the owning
  :class:`~.alerts.AlertDaemon`: *no successful canary against seat X
  for* ``MXNET_TPU_CANARY_ABSENCE_S`` *scaled seconds* walks
  pending→firing even while the seat self-reports healthy — the
  lying-healthz page.

``MXNET_TPU_CANARY=0`` disables the whole subsystem: the router never
constructs a prober, no thread spawns, no family registers.
"""
from __future__ import annotations

import hashlib
import json
import threading
import time
import urllib.error
import urllib.request
from urllib.parse import urlsplit

import numpy as np

from .. import envvars
from . import events as _events
from .alerts import PAGE, AbsenceRule
from .registry import DEFAULT_MS_BUCKETS, REGISTRY
from .trace import new_trace_id

__all__ = ["CanaryProber", "golden_tokens", "response_checksum"]

#: outcome label values (one counter child each, per seat/transport)
OUTCOMES = ("ok", "checksum_mismatch", "timeout", "error")

_TIMEOUT_ERRORS = ("DeadlineExceededError", "TimeoutError")


def golden_tokens(n=16, vocab=1000):
    """The deterministic synthetic request: small (one packed row,
    negligible device time) and identical on every probe so the
    response checksum is comparable across seats and rounds."""
    return (np.arange(n, dtype=np.int32) % max(2, int(vocab) - 1)) + 1


def response_checksum(result):
    """Content checksum of a probe response. Rounded to 3 decimals
    before hashing so benign float jitter across identical replicas
    (bf16 reductions, fused vs unfused lowerings) doesn't page, while
    wrong weights — which move outputs at the first decimal — do."""
    arr = np.asarray(result, dtype=np.float32)
    return hashlib.sha256(
        np.round(arr, 3).tobytes() + str(arr.shape).encode()
    ).hexdigest()[:16]


class CanaryProber:
    """Round-robin black-box prober over a fleet of seats.

    Parameters
    ----------
    targets_fn : ``() -> [target, ...]`` re-read every round (seats
        come and go). A target dict carries ``engine_id`` plus either
        ``url`` (exposition base URL; ``wire_port`` when the seat
        advertises one) or ``engine`` (in-process handle).
    alerts : the owning :class:`~.alerts.AlertDaemon` (usually the
        router's) — one canary-absence PAGE rule per seat is declared
        on it, and removed when the seat leaves the fleet. None (e.g.
        ``MXNET_TPU_SLO=0``) keeps probing + metrics without paging.
    golden : pin ONE fleet-wide golden checksum (a fleet serving one
        model — any seat answering differently is wrong). Default:
        trust-on-first-use PER SEAT — each seat's first successful
        probe pins its own golden (a ``canary_golden`` event records
        it) and later drift on that seat counts
        ``checksum_mismatch``; per-seat goldens also serve fleets
        whose seats legitimately differ (A/B weights, the loadgen's
        per-engine random inits).
    """

    def __init__(self, targets_fn, owner_id="canary", alerts=None,
                 interval_s=None, timeout_s=None, absence_s=None,
                 tokens=None, golden=None, registry=None):
        reg = registry if registry is not None else REGISTRY
        self._registry = reg
        self._targets_fn = targets_fn
        self.owner_id = str(owner_id)
        self._alerts = alerts
        self.interval_s = (float(interval_s) if interval_s is not None
                           else envvars.get("MXNET_TPU_CANARY_INTERVAL_S"))
        self.timeout_s = (float(timeout_s) if timeout_s is not None
                          else envvars.get("MXNET_TPU_CANARY_TIMEOUT_S"))
        self._absence_s = (float(absence_s) if absence_s is not None
                           else envvars.get("MXNET_TPU_CANARY_ABSENCE_S"))
        self._tokens = np.asarray(tokens, np.int32) \
            if tokens is not None else golden_tokens()
        self.golden = str(golden) if golden is not None else None
        self._goldens = {}          # per-seat TOFU when not pinned
        self._gen = {}              # per-seat generation token: a
        # REPLACEMENT seat under a reused id (remove_engine +
        # add_engine, the autoscaler's replace) is a NEW model — its
        # golden re-pins instead of paging checksum_mismatch forever
        # against the dead incarnation's weights
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._transport_rr = {}     # engine_id -> last transport used
        self._wire = {}             # engine_id -> (port, WireClient)
        self._lat_ema = {}          # engine_id -> ok-probe latency EMA
        self._rules = set()         # absence-rule names we declared
        self.rounds = 0
        self._c_req = reg.counter(
            "mxnet_tpu_canary_requests_total",
            "synthetic canary probes by seat, transport and outcome "
            "(ok / checksum_mismatch / timeout / error); tagged "
            "synthetic so cost reconciliation excludes them",
            ("engine_id", "transport", "outcome", "traffic"))
        self._h_lat = reg.histogram(
            "mxnet_tpu_canary_latency_ms",
            "canary probe round-trip latency by seat and transport",
            ("engine_id", "transport", "traffic"),
            buckets=DEFAULT_MS_BUCKETS)
        self._c_billed_s = reg.counter(
            "mxnet_tpu_canary_billed_seconds_total",
            "amortized device seconds billed to canary probes (what "
            "loadgen subtracts from the cost-ledger delta)",
            ("engine_id", "traffic"))
        self._c_billed_req = reg.counter(
            "mxnet_tpu_canary_billed_requests_total",
            "canary probes carrying an amortized cost bill",
            ("engine_id", "traffic"))
        self._c_billed_tok = reg.counter(
            "mxnet_tpu_canary_billed_tokens_total",
            "valid tokens billed to canary probes",
            ("engine_id", "traffic"))
        # the routing-weight input, exported: the per-seat ok-probe
        # latency EMA used to be internal-only, so the signal routing
        # decisions hinge on could be neither historied nor graphed
        self._g_lat_ema = reg.gauge(
            "mxnet_tpu_canary_latency_ema_ms",
            "per-seat successful-probe latency EMA (the black-box "
            "hot-spot signal SLO-aware routing weights fold in); 0 "
            "after a seat replacement resets the EMA",
            ("engine_id", "traffic"))
        # the exemplar↔retrievable-trace contract is serving-owned;
        # imported lazily here (telemetry must stay importable without
        # serving) and resolved once per prober
        try:
            from ..serving.metrics import exemplar_gate, slow_exemplar
            self._exemplars = exemplar_gate()
            self._slow_exemplar = slow_exemplar
        except Exception:
            self._exemplars = False
            self._slow_exemplar = None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"mxnet_tpu_canary_{self.owner_id}")
            self._thread.start()
        _events.emit("canary_start", owner=self.owner_id,
                     interval_s=self.interval_s)
        return self

    def stop(self):
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        with self._lock:
            wires = [entry[-1] for entry in self._wire.values()]
            self._wire.clear()
        for w in wires:
            w.close()

    def _run(self):
        # the FIRST round runs immediately: a fresh fleet must get its
        # first canary success on the books before the absence rule's
        # pending dwell can elapse (at drill window scales the dwell is
        # shorter than one probe interval)
        while True:
            try:
                self.probe_all()
            except Exception as e:
                # one broken round must not kill black-box monitoring
                _events.emit("canary_round_error", owner=self.owner_id,
                             error=repr(e))
            if self._stop.wait(self.interval_s):
                return

    # -- one round ---------------------------------------------------------
    def probe_all(self):
        """Probe every current seat once (round-robin transport per
        seat); reconcile the absence-rule set with the live fleet.
        Returns ``{engine_id: outcome}``."""
        try:
            targets = list(self._targets_fn() or ())
        except Exception as e:
            _events.emit("canary_targets_error", owner=self.owner_id,
                         error=repr(e))
            return {}
        self._sync_rules(targets)
        out = {}
        for t in targets:
            if self._stop.is_set():
                break
            eid = str(t.get("engine_id"))
            transport = self._pick_transport(eid, t)
            t0 = time.perf_counter()
            outcome, cost, trace_id = self._probe(t, transport)
            ms = (time.perf_counter() - t0) * 1e3
            self._record(eid, transport, outcome, ms, cost, trace_id)
            out[eid] = outcome
        self.rounds += 1
        return out

    def _pick_transport(self, eid, target):
        if "engine" in target:
            return "local"
        if not target.get("wire_port"):
            return "http"
        last = self._transport_rr.get(eid)
        nxt = "http" if last == "wire" else "wire"
        self._transport_rr[eid] = nxt
        return nxt

    def _record(self, eid, transport, outcome, ms, cost, trace_id):
        tagged = {"engine_id": eid, "transport": transport,
                  "traffic": "synthetic"}
        self._c_req.labels(outcome=outcome, **tagged).inc()
        if outcome == "ok":
            # per-seat latency EMA: the router's SLO-aware routing
            # reads this as its black-box hot-spot signal
            with self._lock:
                prev = self._lat_ema.get(eid)
                ema = ms if prev is None else 0.5 * prev + 0.5 * ms
                self._lat_ema[eid] = ema
            self._g_lat_ema.labels(engine_id=eid,
                                   traffic="synthetic").set(ema)
        if outcome in ("ok", "checksum_mismatch"):
            exemplar = (self._slow_exemplar(trace_id, ms,
                                            self._exemplars)
                        if self._slow_exemplar is not None else None)
            self._h_lat.labels(**tagged).observe(ms, exemplar=exemplar)
        if cost:
            bill = {"engine_id": eid, "traffic": "synthetic"}
            self._c_billed_s.labels(**bill).inc(
                max(0.0, float(cost.get("device_s") or 0.0)))
            self._c_billed_req.labels(**bill).inc()
            self._c_billed_tok.labels(**bill).inc(
                int(cost.get("tokens") or 0))
        if outcome != "ok":
            _events.emit("canary_probe_failed", owner=self.owner_id,
                         engine_id=eid, transport=transport,
                         outcome=outcome, ms=round(ms, 3),
                         trace_id=trace_id)

    # -- absence rules ------------------------------------------------------
    def _rule_name(self, eid):
        return f"canary_absent_{eid}"

    def _sync_rules(self, targets):
        """One PAGE absence rule per live seat: 'no successful canary
        against seat X over the (scaled) absence window'. Seats that
        left the fleet drop their rule — a removed engine must not
        page forever."""
        if self._alerts is None:
            return
        live = {str(t.get("engine_id")) for t in targets}
        for eid in live:
            name = self._rule_name(eid)
            if name in self._rules:
                continue
            try:
                self._alerts.add_rule(AbsenceRule(
                    name, "mxnet_tpu_canary_requests_total",
                    window=self._absence_s,
                    match={"engine_id": eid, "outcome": "ok",
                           "traffic": "synthetic"},
                    severity=PAGE, for_s=60.0,
                    registry=self._registry))
                self._rules.add(name)
            except ValueError:
                self._rules.add(name)   # declared by a prior prober
        for eid in [r[len("canary_absent_"):] for r in self._rules]:
            if eid not in live:
                self._alerts.remove_rule(self._rule_name(eid))
                self._rules.discard(self._rule_name(eid))

    def latency_ms(self, engine_id):
        """This seat's successful-probe latency EMA (None before its
        first ok probe) — the black-box hot-spot signal the router's
        SLO-aware routing weights fold in."""
        with self._lock:
            return self._lat_ema.get(str(engine_id))

    # -- probes -------------------------------------------------------------
    def golden_for(self, engine_id):
        """The golden checksum this seat is being judged against
        (None before its first successful probe, unless pinned)."""
        if self.golden is not None:
            return self.golden
        with self._lock:
            return self._goldens.get(str(engine_id))

    def _probe(self, target, transport):
        """(outcome, cost_or_None, trace_id) for one probe."""
        trace_id = new_trace_id("canary")
        eid = str(target.get("engine_id"))
        try:
            if transport == "local":
                result, cost = self._probe_local(target, trace_id)
            elif transport == "wire":
                result, cost = self._probe_wire(target, trace_id)
            else:
                result, cost = self._probe_http(target, trace_id)
        except Exception as e:
            name = type(e).__name__
            outcome = ("timeout" if name in _TIMEOUT_ERRORS
                       or "timed out" in str(e) else "error")
            return outcome, None, trace_id
        return (self._check(eid, result, token=target.get("token")),
                cost, trace_id)

    def _check(self, eid, result, token=None):
        checksum = response_checksum(result)
        if self.golden is not None:     # pinned fleet-wide golden
            return ("ok" if checksum == self.golden
                    else "checksum_mismatch")
        regolden = False
        with self._lock:
            if token is not None and self._gen.get(eid) != token:
                # a new seat GENERATION under this id (replacement):
                # the old incarnation's golden is void — re-TOFU.
                # Same-generation weight drift still pages.
                regolden = self._gen.get(eid) is not None
                self._gen[eid] = token
                self._goldens.pop(eid, None)
                if self._lat_ema.pop(eid, None) is not None:
                    # children can't be deleted; zero beats a stale
                    # EMA attributed to the replacement incarnation
                    self._g_lat_ema.labels(
                        engine_id=eid, traffic="synthetic").set(0.0)
            prev = self._goldens.get(eid)
            if prev is None:
                # trust on first use, PER SEAT: this seat's first
                # healthy answer is its golden — recorded so an
                # operator can pin it fleet-wide
                self._goldens[eid] = checksum
        if regolden:
            _events.emit("canary_regolden", owner=self.owner_id,
                         engine_id=eid, token=str(token))
        if prev is None:
            _events.emit("canary_golden", owner=self.owner_id,
                         engine_id=eid, checksum=checksum)
            return "ok"
        return "ok" if checksum == prev else "checksum_mismatch"

    def _probe_local(self, target, trace_id):
        fut = target["engine"].submit(
            self._tokens, deadline_ms=self.timeout_s * 1e3,
            trace_id=trace_id)
        result = fut.result(timeout=self.timeout_s)
        return result, getattr(fut, "cost", None)

    def _probe_http(self, target, trace_id):
        payload = {"tokens": self._tokens.tolist(),
                   "token_types": None,
                   "deadline_ms": self.timeout_s * 1e3,
                   "trace_id": trace_id,
                   "timeout_s": self.timeout_s}
        req = urllib.request.Request(
            target["url"].rstrip("/") + "/submit",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s + 1.0) as r:
                body = json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            try:
                body = json.loads(e.read().decode())
            except Exception:
                raise OSError(f"HTTP {e.code}") from e
        if not body.get("ok"):
            if body.get("error_type") in _TIMEOUT_ERRORS:
                raise TimeoutError(body.get("error") or "canary timeout")
            raise OSError(body.get("error") or "canary dispatch error")
        return (np.asarray(body["result"], np.float32),
                body.get("cost"))

    def _probe_wire(self, target, trace_id):
        wc = self._wire_client(target)
        payload = {"tokens": self._tokens,
                   "token_types": None,
                   "deadline_ms": self.timeout_s * 1e3,
                   "trace_id": trace_id,
                   "span_id": None}
        box = {}
        evt = threading.Event()

        def _done(exc, body):
            box["exc"], box["body"] = exc, body
            evt.set()

        wc.dispatch(payload, _done, self.timeout_s)
        if not evt.wait(self.timeout_s + 1.0):
            raise TimeoutError("canary wire probe timed out")
        if box.get("exc") is not None:
            raise box["exc"]
        body = box["body"] or {}
        if body.get("error_type") is not None:
            if body["error_type"] in _TIMEOUT_ERRORS:
                raise TimeoutError(body.get("error") or "canary timeout")
            raise OSError(body.get("error") or "canary wire error")
        return np.asarray(body.get("result")), body.get("cost")

    def _wire_client(self, target):
        """The prober's OWN persistent wire connection per seat —
        probing over the router's dispatch pool would share its fate
        (and its correlation slots); black-box means independent. The
        handshake pins the seat's advertised engine identity (same
        defense as the router's dispatch pool): a replacement engine
        on a recycled port is refused, never probed — or trust-on-
        first-use goldened — under the old seat's name."""
        from ..serving.wire import WireClient, WireError

        eid = str(target.get("engine_id"))
        port = int(target["wire_port"])
        peer = target.get("wire_engine_id")
        peer = str(peer) if peer is not None else None
        host = urlsplit(target["url"]).hostname or "127.0.0.1"
        with self._lock:
            known = self._wire.get(eid)
        if known is not None and (known[0] != port
                                  or (peer is not None
                                      and known[1] not in (None, peer))):
            known[2].close()
            known = None
        if known is None:
            wc = WireClient(host, port, conns=1,
                            client_id=f"canary-{self.owner_id}",
                            expect_engine_id=peer,
                            timeout_s=min(self.timeout_s, 5.0))
            with self._lock:
                self._wire[eid] = (port, peer, wc)
            known = (port, peer, wc)
        wc = known[2]
        # blocking connect/handshake is fine HERE: the prober thread
        # owns its own cadence (this is not a dispatch hot path)
        if wc.ensure() == 0:
            raise WireError(f"no canary wire connection to {host}:{port}")
        wc.sweep()
        return wc
