"""Alert rules over declared SLOs: the judging half of the SLO engine.

:mod:`.slo` computes (SLIs, burn rates, budgets); this module decides
and escalates. An :class:`AlertDaemon` owns one
:class:`~.slo.SloEvaluator` and a set of declared rules, ticks them on
a background thread every ``MXNET_TPU_SLO_EVAL_S`` seconds, and walks
each rule through the classic state machine::

    inactive → pending (condition true, waiting out ``for_s``)
             → firing  (condition held)
             → resolved (condition cleared; listed for
                         ``MXNET_TPU_ALERT_RESOLVED_KEEP_S``, then
                         back to inactive)

Rule kinds:

- :class:`BurnRateRule` — the SRE-workbook multi-window multi-burn-rate
  shape: fire only when the error budget burns faster than ``factor``×
  sustainable over BOTH a long window (enough evidence) and a short
  window (still happening right now). The default pairs are the
  workbook's: **page** = 1h long / 5m short at 14.4× (2% of a 30-day
  budget in one hour), **ticket** = 6h long / 30m short at 6× (5% in
  six hours).
- :class:`ThresholdRule` — a threshold objective (cost budget, gauge
  bound) violated over a window: its ``burn_rate`` (violation
  multiple) exceeds ``factor`` (default 1.0 = at the bound).
- :class:`AbsenceRule` — a metric family (or labeled slice) that
  stopped moving: no increase over the window, or the family was
  never created at all. Heartbeats and scrape targets alert this way.

Every transition emits an ``alert_state`` run event and bumps
``mxnet_tpu_alerts_transitions_total{alert,to}``;
``mxnet_tpu_alerts_state{alert,severity}`` tracks the live position
(0 inactive/resolved, 1 pending, 2 firing) and
``mxnet_tpu_alerts_firing{owner,severity}`` counts what's burning. A rule
entering **firing** at ``page`` severity dumps a flight-recorder
bundle whose meta carries the alert payload — burn-rate history and,
for latency objectives, the OpenMetrics exemplars whose trace ids
resolve at ``/traces/<id>``. The daemon also registers an
``alerts_<owner>`` bundle section, so a watchdog- or crash-triggered
bundle explains the alert state too (and the recorder's shared-window
dedupe means a watchdog trip and a page firing together produce ONE
bundle tagged with both causes).

``/alerts`` on the owner's exposition server serves
:meth:`AlertDaemon.snapshot`.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque

from .. import envvars
from . import events as _events
from . import recorder as _recorder
from .registry import REGISTRY
from .slo import LatencySLO, ThresholdSLO, _match_labels

__all__ = ["AlertRule", "BurnRateRule", "ThresholdRule", "AbsenceRule",
           "AlertDaemon", "default_serving_objectives",
           "default_tenant_objectives", "default_router_objectives",
           "default_burn_rules", "PAGE", "TICKET"]

PAGE = "page"
TICKET = "ticket"

#: SRE-workbook multi-window pairs: (long, short, factor, for_s)
_PAGE_WINDOWS = ("1h", "5m", 14.4, 60.0)
_TICKET_WINDOWS = ("6h", "30m", 6.0, 300.0)

_STATE_VALUE = {"inactive": 0, "resolved": 0, "pending": 1, "firing": 2}


class AlertRule:
    """One declared rule: a name, a severity, and a condition over an
    evaluator. ``for_s`` is the pending dwell (scaled by the
    evaluator's window scale, like every other SLO duration) before a
    true condition escalates to firing."""

    kind = "rule"

    def __init__(self, name, severity=TICKET, for_s=0.0):
        if severity not in (PAGE, TICKET):
            raise ValueError(f"severity must be page/ticket, "
                             f"got {severity!r}")
        self.name = str(name)
        self.severity = severity
        self.for_s = float(for_s)

    def sample(self, evaluator, now):
        """Per-tick raw sampling hook (absence rules record their
        series here); default rules read what the evaluator sampled."""

    def condition(self, evaluator, now):
        """``(active, detail)`` — active None means "not enough data"
        (treated as not active: an idle or freshly started process
        must not page on ignorance)."""
        raise NotImplementedError

    def slo_name(self):
        return None

    def describe(self):
        return {"alert": self.name, "kind": self.kind,
                "severity": self.severity, "for_s": self.for_s}


class BurnRateRule(AlertRule):
    """Multi-window multi-burn-rate over one declared SLO: fires when
    the error budget burns faster than ``factor``× sustainable over
    BOTH windows. Windows are labels into the evaluator's canonical
    set (``"5m"``/``"30m"``/``"1h"``/``"6h"``) or raw seconds."""

    kind = "burn_rate"

    def __init__(self, name, slo, long_window="1h", short_window="5m",
                 factor=14.4, severity=PAGE, for_s=60.0):
        super().__init__(name, severity, for_s)
        self.slo = str(slo)
        self.long_window = long_window
        self.short_window = short_window
        self.factor = float(factor)

    def slo_name(self):
        return self.slo

    def condition(self, evaluator, now):
        slo = evaluator.get(self.slo)
        if slo is None:
            return None, {"error": f"unknown SLO {self.slo!r}"}
        long_s = evaluator.window_s(self.long_window)
        short_s = evaluator.window_s(self.short_window)
        b_long = slo.burn_rate(evaluator.store, long_s, now)
        b_short = slo.burn_rate(evaluator.store, short_s, now)
        detail = {"burn_long": (round(b_long, 4)
                                if b_long is not None else None),
                  "burn_short": (round(b_short, 4)
                                 if b_short is not None else None),
                  "factor": self.factor,
                  "long_window": self.long_window,
                  "short_window": self.short_window}
        if b_long is None or b_short is None:
            return None, detail
        return (b_long > self.factor and b_short > self.factor), detail

    def describe(self):
        return dict(super().describe(), slo=self.slo,
                    long_window=str(self.long_window),
                    short_window=str(self.short_window),
                    factor=self.factor)


class ThresholdRule(AlertRule):
    """A threshold objective violated over a window: the SLO's
    violation multiple (``burn_rate``: value/bound, or bound/value for
    lower-is-bad) exceeds ``factor``."""

    kind = "threshold"

    def __init__(self, name, slo, window="30m", factor=1.0,
                 severity=TICKET, for_s=300.0):
        super().__init__(name, severity, for_s)
        self.slo = str(slo)
        self.window = window
        self.factor = float(factor)

    def slo_name(self):
        return self.slo

    def condition(self, evaluator, now):
        slo = evaluator.get(self.slo)
        if slo is None:
            return None, {"error": f"unknown SLO {self.slo!r}"}
        w = evaluator.window_s(self.window)
        burn = slo.burn_rate(evaluator.store, w, now)
        value = (slo.value(evaluator.store, w, now)
                 if isinstance(slo, ThresholdSLO) else None)
        detail = {"burn": round(burn, 4) if burn is not None else None,
                  "value": (round(value, 6) if value is not None
                            else None),
                  "bound": slo.target, "factor": self.factor,
                  "window": str(self.window)}
        if burn is None:
            return None, detail
        return burn > self.factor, detail

    def describe(self):
        return dict(super().describe(), slo=self.slo,
                    window=str(self.window), factor=self.factor)


class AbsenceRule(AlertRule):
    """A cumulative family (or labeled slice of one) that stopped
    moving — no increase over the window — or that was never created
    at all. The daemon samples the matched sum every tick into the
    evaluator's store under a private key, so the delta math is the
    same partial-coverage-honest machinery the SLOs use."""

    kind = "absence"

    def __init__(self, name, family, window="5m", match=None,
                 severity=TICKET, for_s=0.0, registry=None):
        super().__init__(name, severity, for_s)
        self.family = str(family)
        self.window = window
        self.match = dict(match or {})
        self.registry = registry if registry is not None else REGISTRY

    def _key(self):
        return f"__absence__:{self.name}"

    def sample(self, evaluator, now):
        fam = self.registry.get(self.family)
        if fam is None:
            return
        total = 0.0
        for values, child in fam._sorted_children():
            if not _match_labels(fam.labelnames, values, self.match):
                continue
            total += (child.count if hasattr(child, "cumulative")
                      else child.value)
        evaluator.store.record(self._key(), now, total)

    def condition(self, evaluator, now):
        fam = self.registry.get(self.family)
        detail = {"family": self.family, "match": self.match,
                  "window": str(self.window)}
        if fam is None:
            # never created: absent by definition (a renamed family
            # upstream fails mxlint, but a dead subsystem lands here)
            return True, dict(detail, absent="family")
        w = evaluator.window_s(self.window)
        d = evaluator.store.delta(self._key(), w, now)
        if d is None:
            return None, detail
        delta, span = d
        detail["delta"] = round(delta, 6)
        if span < 0.9 * w:
            # "nothing moved over the window" is undecidable on
            # history SHORTER than the window: the partial-coverage
            # fallback that is honest for burn rates would page a
            # freshly declared rule off one quiet second (the canary
            # startup false-page) — not enough data, never a page
            detail["span_s"] = round(span, 3)
            return None, detail
        return delta <= 0, detail

    def describe(self):
        return dict(super().describe(), family=self.family,
                    match=self.match, window=str(self.window))


class _AlertStatus:
    """Runtime position of one rule in the state machine."""

    __slots__ = ("rule", "state", "since_mono", "since_wall",
                 "fired_at", "resolved_at", "detail", "history")

    def __init__(self, rule, history):
        self.rule = rule
        self.state = "inactive"
        self.since_mono = time.monotonic()
        self.since_wall = time.time()
        self.fired_at = None
        self.resolved_at = None
        self.detail = {}
        self.history = deque(maxlen=history)   # (wall_ts, detail)


class AlertDaemon:
    """Background evaluation loop: tick the evaluator, step every
    rule's state machine, publish gauges/events, escalate pages.

    ``on_page`` overrides the page escalation (default: a
    flight-recorder bundle via :func:`~.recorder.dump` whose meta
    carries the alert payload). The daemon can also be driven manually
    with :meth:`evaluate_once` (tests, or an owner that already has a
    poll loop).
    """

    def __init__(self, evaluator, eval_s=None, resolved_keep_s=None,
                 history=None, registry=None, on_page=None):
        self.evaluator = evaluator
        self.owner_id = evaluator.owner_id
        reg = registry if registry is not None else REGISTRY
        self.eval_s = (float(eval_s) if eval_s is not None
                       else envvars.get("MXNET_TPU_SLO_EVAL_S"))
        scale = evaluator.scale
        self.resolved_keep_s = (
            float(resolved_keep_s) if resolved_keep_s is not None
            else envvars.get("MXNET_TPU_ALERT_RESOLVED_KEEP_S") * scale)
        self._history_len = (int(history) if history is not None
                             else envvars.get("MXNET_TPU_ALERT_HISTORY"))
        self._on_page = on_page
        # optional "why slow" override: a callable returning top-stage
        # attribution rows for the payload of firing latency alerts.
        # Defaults to this owner's own aggregator; a ROUTER points it
        # at its fleet /whyslow merge so the fleet page names the
        # bottleneck stage even when every seat is out-of-process.
        self.attribution_fn = None
        self._rules = OrderedDict()     # name -> _AlertStatus
        self._listeners = []            # fn(transition_record)
        self._transitions = deque(maxlen=self._history_len)
        self._lock = threading.Lock()
        self._thread = None
        self._stop = threading.Event()
        self._section = f"alerts_{self.owner_id}"
        self._g_state = reg.gauge(
            "mxnet_tpu_alerts_state",
            "alert state-machine position (0 inactive/resolved, "
            "1 pending, 2 firing)", ("alert", "severity"))
        # owner-labeled: a router and its engines run N+1 daemons in
        # ONE process registry — absolute sets on a severity-only
        # family would clobber each other (sum by severity in PromQL)
        self._g_firing = reg.gauge(
            "mxnet_tpu_alerts_firing",
            "alerts currently firing, by owner and severity",
            ("owner", "severity"))
        self._c_transitions = reg.counter(
            "mxnet_tpu_alerts_transitions_total",
            "alert state transitions, by alert and destination state",
            ("alert", "to"))

    # -- rule set ----------------------------------------------------------
    def add_rule(self, rule):
        with self._lock:
            if rule.name in self._rules:
                raise ValueError(f"alert {rule.name!r} already declared")
            self._rules[rule.name] = _AlertStatus(rule,
                                                  self._history_len)
        self._g_state.labels(alert=self._label(rule),
                             severity=rule.severity).set(0)
        return rule

    def remove_rule(self, name):
        """Retire one rule (the canary prober drops a removed seat's
        absence rule this way — a seat that LEFT the fleet must not
        page forever). A rule retired while PENDING/FIRING emits a
        final ``resolved`` transition (tagged ``removed``) so the
        incident tracker releases its firing hold and the egress
        notifier delivers the clearing notification — silently
        popping a firing page would leave the incident open and the
        pager waiting forever. Its state gauge zeroes; history stays
        in the transition log."""
        with self._lock:
            st = self._rules.pop(name, None)
            listeners = list(self._listeners)
        if st is None:
            return False
        rule = st.rule
        self._g_state.labels(alert=self._label(rule),
                             severity=rule.severity).set(0)
        if st.state in ("pending", "firing"):
            rec = {"alert": rule.name, "owner": self.owner_id,
                   "severity": rule.severity, "from": st.state,
                   "to": "resolved", "ts": round(time.time(), 3),
                   "detail": dict(st.detail, removed=True)}
            self._c_transitions.labels(alert=self._label(rule),
                                       to="resolved").inc()
            with self._lock:
                self._transitions.append(rec)
            _events.emit("alert_state", **rec)
            for fn in listeners:
                try:
                    fn(dict(rec))
                except Exception as e:
                    _events.emit("alert_listener_error",
                                 owner=self.owner_id, alert=rule.name,
                                 error=repr(e))
        return True

    def add_listener(self, fn):
        """Register ``fn(transition_record)`` called on EVERY state
        transition (after the ``alert_state`` event and counters) —
        the alert-egress notifier attaches here. Listener failures are
        contained (an ``alert_listener_error`` event, never a dead
        evaluation loop)."""
        with self._lock:
            if fn not in self._listeners:
                self._listeners.append(fn)
        return fn

    def remove_listener(self, fn):
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def _label(self, rule):
        return f"{self.owner_id}:{rule.name}"

    def get(self, name):
        with self._lock:
            st = self._rules.get(name)
            return st.rule if st is not None else None

    def state(self, name):
        with self._lock:
            st = self._rules.get(name)
            return st.state if st is not None else None

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"mxnet_tpu_alerts_{self.owner_id}")
            self._thread.start()
        # every flight bundle from this process now explains the alert
        # state too (watchdog trips and page firings share bundles via
        # the recorder's dedupe window)
        _recorder.add_bundle_section(self._section, self.snapshot)
        # alert egress: when the process notifier is configured
        # (MXNET_TPU_ALERT_EGRESS + a sink), this daemon's transitions
        # ride out through it — one delivery pipeline per process, the
        # fingerprint dedup keeps N daemons from double-paging
        try:
            from . import egress as _egress
            notifier = _egress.default_notifier()
            if notifier is not None:
                self.add_listener(notifier.notify)
        except Exception as e:
            _events.emit("alert_egress_error", owner=self.owner_id,
                         error=repr(e))
        return self

    def stop(self):
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        _recorder.remove_bundle_section(self._section)

    def _run(self):
        while not self._stop.wait(self.eval_s):
            try:
                self.evaluate_once()
            except Exception as e:
                # one broken evaluation must not kill alerting
                _events.emit("alert_eval_error", owner=self.owner_id,
                             error=repr(e))

    # -- evaluation --------------------------------------------------------
    def evaluate_once(self, now=None):
        """One tick: sample, evaluate, step state machines. Returns
        ``{alert: state}``."""
        now = self.evaluator.tick(now)
        with self._lock:
            statuses = list(self._rules.values())
        wall = time.time()
        firing = {PAGE: 0, TICKET: 0}
        out = {}
        for st in statuses:
            st.rule.sample(self.evaluator, now)
            active, detail = st.rule.condition(self.evaluator, now)
            # under the lock: a concurrent /alerts scrape (or bundle
            # write) iterates the history deque
            with self._lock:
                st.detail = detail
                st.history.append((round(wall, 3), detail))
            self._step(st, bool(active) if active is not None else False,
                       now)
            if st.state == "firing":
                firing[st.rule.severity] += 1
            out[st.rule.name] = st.state
        for sev, n in firing.items():
            self._g_firing.labels(owner=self.owner_id,
                                  severity=sev).set(n)
        return out

    def _step(self, st, active, now):
        rule = st.rule
        for_s = rule.for_s * self.evaluator.scale
        new = st.state
        if st.state in ("inactive", "resolved"):
            if active:
                new = "pending" if for_s > 0 else "firing"
            elif (st.state == "resolved"
                    and now - st.since_mono > self.resolved_keep_s):
                new = "inactive"
        elif st.state == "pending":
            if not active:
                new = "inactive"
            elif now - st.since_mono >= for_s:
                new = "firing"
        elif st.state == "firing":
            if not active:
                new = "resolved"
        if new == st.state:
            return
        prev, st.state = st.state, new
        st.since_mono = now
        st.since_wall = time.time()
        if new == "firing":
            st.fired_at = st.since_wall
        if new == "resolved":
            st.resolved_at = st.since_wall
        self._g_state.labels(alert=self._label(rule),
                             severity=rule.severity) \
            .set(_STATE_VALUE[new])
        self._c_transitions.labels(alert=self._label(rule),
                                   to=new).inc()
        rec = {"alert": rule.name, "owner": self.owner_id,
               "severity": rule.severity, "from": prev, "to": new,
               "ts": round(st.since_wall, 3), "detail": st.detail}
        with self._lock:
            self._transitions.append(rec)
            listeners = list(self._listeners)
        # the alert_state event goes FIRST (the incident tracker taps
        # it and opens/updates the incident), THEN listeners — so the
        # egress notifier finds the incident id already minted
        _events.emit("alert_state", **rec)
        for fn in listeners:
            try:
                fn(dict(rec))
            except Exception as e:
                _events.emit("alert_listener_error",
                             owner=self.owner_id, alert=rule.name,
                             error=repr(e))
        if new == "firing" and rule.severity == PAGE:
            self._page(st)

    def _page(self, st):
        payload = self._alert_payload(st)
        if self._on_page is not None:
            try:
                self._on_page(payload)
            except Exception as e:
                _events.emit("alert_page_error", owner=self.owner_id,
                             alert=st.rule.name, error=repr(e))
            return
        # default escalation: a flight bundle carrying the alert, its
        # burn-rate history and the exemplar evidence. The recorder's
        # shared dedupe window folds this with a concurrent watchdog
        # trip into ONE bundle tagged with both causes.
        _recorder.RECORDER.dump(f"alert_{st.rule.name}",
                                extra={"alert": payload})

    # -- surfaces ----------------------------------------------------------
    def _alert_payload(self, st, history=32):
        rule = st.rule
        with self._lock:
            state = (st.state, round(st.since_wall, 3), st.fired_at,
                     st.resolved_at, st.detail,
                     list(st.history)[-int(history):])
        out = dict(rule.describe(), owner=self.owner_id,
                   state=state[0], since=state[1],
                   fired_at=state[2], resolved_at=state[3],
                   detail=state[4], burn_history=state[5])
        name = rule.slo_name()
        slo = self.evaluator.get(name) if name else None
        if slo is not None:
            row = self.evaluator.evaluate(slo)
            out["error_budget_remaining"] = row.get(
                "error_budget_remaining")
            out["slo_target"] = slo.target
            if isinstance(slo, LatencySLO):
                exemplars = slo.exemplars()
                # the alert surface promises RETRIEVABLE evidence:
                # drop exemplars whose trace the bounded tail-sampling
                # ring has already evicted (keep the raw list only
                # when nothing survives — a value-only hint still
                # beats none)
                try:
                    from . import spans as _spans
                    live = [e for e in exemplars
                            if _spans.get_trace(e["trace_id"])
                            is not None]
                except Exception:
                    live = []
                out["exemplars"] = live or exemplars
                # "why slow" rides the page: the owner's current
                # top-stage attribution (lazy import + peek-no-create:
                # a process without attribution never mints the stage
                # families just because an alert was described)
                try:
                    if self.attribution_fn is not None:
                        top = self.attribution_fn()
                    else:
                        from . import attribution as _attribution
                        top = _attribution.top_stages_for(
                            self.owner_id)
                except Exception:
                    top = None
                if top:
                    out["attribution"] = top
        return out

    def snapshot(self):
        """The ``/alerts`` body (also the bundle section): every
        rule's position, evidence and history, firing/pending counts,
        and the recent transition log."""
        with self._lock:
            statuses = list(self._rules.values())
            transitions = list(self._transitions)
        rules = [self._alert_payload(st, history=8) for st in statuses]
        return {"owner": self.owner_id,
                "eval_s": self.eval_s,
                "window_scale": self.evaluator.scale,
                "firing": sum(1 for r in rules
                              if r["state"] == "firing"),
                "pending": sum(1 for r in rules
                               if r["state"] == "pending"),
                "rules": rules,
                "transitions": transitions[-32:]}


# -- default objective/rule sets --------------------------------------------

def default_serving_objectives(evaluator, engine_id):
    """The default engine objective set (ISSUE defaults, knob-tuned):
    latency quantile, availability, and — when a budget is declared —
    cost per 1k tokens. Returns the added SLO names."""
    from .slo import AvailabilitySLO, CostSLO

    names = []
    evaluator.add(LatencySLO(
        "serving_latency",
        threshold_ms=envvars.get("MXNET_TPU_SLO_LATENCY_MS"),
        target=envvars.get("MXNET_TPU_SLO_LATENCY_TARGET"),
        match={"engine_id": engine_id, "stage": "total"},
        description="requests completing under the latency bound"))
    names.append("serving_latency")
    evaluator.add(AvailabilitySLO(
        "serving_availability",
        target=envvars.get("MXNET_TPU_SLO_AVAILABILITY_TARGET"),
        match={"engine_id": engine_id},
        description="requests completed (not shed/errored/expired)"))
    names.append("serving_availability")
    budget = envvars.get("MXNET_TPU_SLO_COST_S_PER_1K")
    if budget is not None:
        evaluator.add(CostSLO(
            "serving_cost", budget, match={"engine_id": engine_id},
            description="device seconds per 1k valid tokens"))
        names.append("serving_cost")
    return names


#: Per-class latency-bound multipliers over MXNET_TPU_SLO_LATENCY_MS:
#: priority buys a tighter bound than the engine-wide objective,
#: best-effort a much looser one (it exists to be shed first, not to
#: page first).
_TENANT_SLO_FACTORS = {"priority": 0.5, "standard": 1.0,
                       "best-effort": 4.0}


def default_tenant_objectives(evaluator, engine_id, classes=None):
    """Per-admission-class latency objectives over the tenant slice
    family: one ``LatencySLO`` per class on
    ``mxnet_tpu_serving_tenant_latency_ms`` with
    ``match={engine_id, tenant_class}`` (label SUBSET matching — the
    tenant/model labels stay free axes). Thresholds default to the
    engine latency bound scaled by class (0.5x / 1x / 4x for
    priority / standard / best-effort), overridable per class with
    ``MXNET_TPU_TENANT_SLO_MS``. Returns the added SLO names."""
    from ..serving import tenancy

    base = float(envvars.get("MXNET_TPU_SLO_LATENCY_MS"))
    overrides = tenancy.class_slo_ms()
    names = []
    for cls in (classes if classes is not None
                else tenancy.TENANT_CLASSES):
        cls = tenancy.normalize_class(cls)
        threshold = overrides.get(
            cls, base * _TENANT_SLO_FACTORS.get(cls, 1.0))
        name = f"tenant_{cls.replace('-', '_')}_latency"
        evaluator.add(LatencySLO(
            name, threshold_ms=threshold,
            target=envvars.get("MXNET_TPU_SLO_LATENCY_TARGET"),
            family="mxnet_tpu_serving_tenant_latency_ms",
            match={"engine_id": engine_id, "tenant_class": cls},
            description=f"{cls}-class requests completing under "
                        f"{threshold:g} ms"))
        names.append(name)
    return names


def default_decode_objectives(evaluator, engine_id):
    """The decode-engine objective set: the serving defaults PLUS the
    inter-token latency quantile — the SLI that makes a stuttering
    token stream page even while whole-request latency still looks
    fine. Returns the added SLO names."""
    names = default_serving_objectives(evaluator, engine_id)
    evaluator.add(LatencySLO(
        "decode_inter_token",
        threshold_ms=envvars.get("MXNET_TPU_SLO_INTER_TOKEN_MS"),
        target=envvars.get("MXNET_TPU_SLO_LATENCY_TARGET"),
        family="mxnet_tpu_serving_inter_token_latency_ms",
        match={"engine_id": engine_id},
        description="generated tokens arriving under the inter-token "
                    "latency bound"))
    names.append("decode_inter_token")
    return names


def default_router_objectives(evaluator, router):
    """The default fleet objective set: availability across failover
    (router outcomes), fleet latency quantile, and the routable-engine
    fraction."""
    from .slo import AvailabilitySLO, GaugeSLO

    names = []
    evaluator.add(LatencySLO(
        "fleet_latency",
        threshold_ms=envvars.get("MXNET_TPU_SLO_LATENCY_MS"),
        target=envvars.get("MXNET_TPU_SLO_LATENCY_TARGET"),
        family="mxnet_tpu_router_latency_ms",
        match={"stage": "total"},
        description="router-observed end-to-end latency objective"))
    names.append("fleet_latency")
    evaluator.add(AvailabilitySLO(
        "fleet_availability",
        target=envvars.get("MXNET_TPU_SLO_AVAILABILITY_TARGET"),
        family="mxnet_tpu_router_requests_total",
        good_events=("completed",),
        bad_events=("failed", "expired", "shed_queue_full",
                    "shed_no_engine", "rejected_stopped", "cancelled"),
        description="fleet availability across failover: requests "
                    "completed vs shed/failed/expired"))
    names.append("fleet_availability")

    def up_fraction():
        board = router.scoreboard()
        if not board:
            return 0.0
        return (sum(1 for r in board.values() if r["routable"])
                / float(len(board)))

    evaluator.add(GaugeSLO(
        "fleet_engines_up",
        target=envvars.get("MXNET_TPU_SLO_ENGINE_UP_FRACTION"),
        op="ge", value_fn=up_fraction,
        description="fraction of registered engines routable"))
    names.append("fleet_engines_up")
    return names


def default_burn_rules(daemon, slo_names):
    """The SRE-workbook rule pair per ratio objective (fast 5m/1h page
    + slow 30m/6h ticket); threshold objectives get a ticket threshold
    rule. Returns the added rule names."""
    from .slo import RatioSLO

    added = []
    for name in slo_names:
        slo = daemon.evaluator.get(name)
        if slo is None:
            continue
        if isinstance(slo, RatioSLO):
            lw, sw, factor, for_s = _PAGE_WINDOWS
            daemon.add_rule(BurnRateRule(
                f"{name}_fast_burn", name, long_window=lw,
                short_window=sw, factor=factor, severity=PAGE,
                for_s=for_s))
            lw, sw, factor, for_s = _TICKET_WINDOWS
            daemon.add_rule(BurnRateRule(
                f"{name}_slow_burn", name, long_window=lw,
                short_window=sw, factor=factor, severity=TICKET,
                for_s=for_s))
            added += [f"{name}_fast_burn", f"{name}_slow_burn"]
        else:
            daemon.add_rule(ThresholdRule(
                f"{name}_over_budget", name, severity=TICKET))
            added.append(f"{name}_over_budget")
    return added
