"""Always-on continuous sampling profiler (Google-Wide-Profiling style).

The existing profiler (:mod:`mxnet_tpu.profiler`) is a *session* tool:
you turn it on, run a workload, dump a Chrome trace. A production
serving fleet needs the complementary *always-on* layer — "where is
host time going RIGHT NOW" — cheap enough to never turn off. This
module is that layer, stdlib-only:

- one daemon thread wakes at ``MXNET_TPU_PROF_HZ`` (default ~19 Hz —
  deliberately off any round period) and snapshots every thread's
  Python stack via ``sys._current_frames()``;
- samples aggregate into **bounded folded-stack counts** keyed by
  ``(thread name, (frame, frame, ...))`` — the Brendan-Gregg collapsed
  format, flamegraph-ready as text. Frames fold by ``function (file)``
  (no line numbers) so the table stays small and stable; the table is
  capped at ``MXNET_TPU_PROF_MAX_STACKS`` entries with overflow folded
  into a per-thread ``(stack-table-full)`` bucket, so a pathological
  workload can grow the *counts*, never the *process*;
- the same daemon runs the resource sweep (:mod:`.resources` — host
  RSS/fds/threads + device memory gauges and watermarks) every
  ``MXNET_TPU_PROF_RESOURCE_S`` seconds, and refreshes the
  ``mxnet_tpu_prof_top_self_frac{frame=...}`` gauge family (the
  Grafana top-functions table) every couple of seconds.

Consumption: ``GET /profile`` on every exposition server (collapsed
text; ``?format=json`` for the top-self-time summary),
``tools/telemetry_dump.py --profile``, a ``profile.txt`` section in
every flight-recorder bundle, and ``profile_top`` in per-leg bench
records.

Cost: one stack walk per live thread per tick — tens of microseconds
at the default rate, invisible next to a model forward. With
``MXNET_TPU_PROF=0`` the daemon never starts and
:func:`ensure_started` is a single env-registry read.
"""
from __future__ import annotations

import atexit
import os
import sys
import threading
import time

from .. import envvars
from . import events as _events
from . import resources as _resources
from .registry import REGISTRY

__all__ = ["ContinuousProfiler", "PROFILER", "ensure_started",
           "collapsed_text", "top_self", "profile_snapshot"]

_TOP_GAUGE_N = 15         # frames exported to the Grafana table
_EXPORT_EVERY_S = 2.0     # top-frame gauge refresh period


class ContinuousProfiler:
    """Sampling daemon + bounded folded-stack aggregation."""

    def __init__(self, hz=None, max_stacks=None, max_depth=None,
                 registry=None):
        reg = registry if registry is not None else REGISTRY
        self.hz = float(hz if hz is not None
                        else envvars.get("MXNET_TPU_PROF_HZ"))
        self.max_stacks = int(max_stacks if max_stacks is not None
                              else envvars.get("MXNET_TPU_PROF_MAX_STACKS"))
        self.max_depth = int(max_depth if max_depth is not None
                             else envvars.get("MXNET_TPU_PROF_MAX_DEPTH"))
        self.resource_s = envvars.get("MXNET_TPU_PROF_RESOURCE_S")
        self._lock = threading.Lock()
        self._counts = {}       # (thread_name, stack tuple) -> samples
        self._samples = 0       # sampler wakeups
        self._errors = 0
        self._thread = None
        self._stop = threading.Event()
        self._started_mono = None
        self._exported = set()  # frames currently on the top gauge
        self._last_leaf = {}    # leaf-self counts at the last export
        self._last_total = 0    # total samples at the last export
        self._c_samples = reg.counter(
            "mxnet_tpu_prof_samples_total",
            "continuous-profiler sampler wakeups")
        self._g_stacks = reg.gauge(
            "mxnet_tpu_prof_distinct_stacks",
            "distinct (thread, folded stack) entries held")
        self._c_overflow = reg.counter(
            "mxnet_tpu_prof_overflow_total",
            "samples folded into (stack-table-full) because the "
            "bounded stack table was full")
        self._g_top = reg.gauge(
            "mxnet_tpu_prof_top_self_frac",
            "fraction of RECENT thread-samples (since the previous "
            "~2 s export) whose LEAF frame is this one — the 'where "
            "is host time going right now' signal (top-N only; "
            "dropped frames reset to 0)", ("frame",))

    # -- lifecycle ---------------------------------------------------------
    def configure(self, hz=None, max_stacks=None, max_depth=None,
                  resource_s=None):
        """Runtime tuning (tests raise hz to converge fast). Takes
        effect on the next sampler wakeup."""
        if hz is not None:
            self.hz = float(hz)
        if max_stacks is not None:
            self.max_stacks = int(max_stacks)
        if max_depth is not None:
            self.max_depth = int(max_depth)
        if resource_s is not None:
            self.resource_s = float(resource_s)
        return self

    @property
    def running(self):
        t = self._thread
        return t is not None and t.is_alive()

    def start(self):
        """Start the daemon (idempotent) and register the
        flight-recorder ``profile.txt`` bundle section. An atexit hook
        stops the sampler BEFORE interpreter teardown: the resource
        sweep calls into jax, and a daemon thread inside the PJRT
        client while it is being destroyed aborts the process
        ("terminate called without an active exception")."""
        with self._lock:
            spawned = not (self._thread is not None
                           and self._thread.is_alive())
            if spawned:
                self._stop.clear()
                self._started_mono = time.monotonic()
                self._thread = threading.Thread(
                    target=self._run, name="mxnet_tpu_prof", daemon=True)
                self._thread.start()
        with _atexit_lock:
            _live.add(self)
        _register_atexit_stop()
        # (re-)register on EVERY start: the section name is shared
        # process-wide, and another instance's stop() may have taken
        # it — an already-running profiler must still heal it
        from . import recorder as _recorder
        _recorder.add_bundle_section("profile.txt", self.collapsed_text)
        if spawned:
            _events.emit("prof_start", hz=self.hz,
                         max_stacks=self.max_stacks)
        return self

    def stop(self):
        """Tests only: halt the sampler (counts are kept)."""
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        with _atexit_lock:
            _live.discard(self)
        if t is not None:
            t.join(timeout=5.0)
        from . import recorder as _recorder
        # only drop the bundle section when it is OURS — a short-lived
        # instance stopping must not strip the process profiler's —
        # and heal it back to the still-running process profiler if
        # this instance had taken the shared name over
        if _recorder.RECORDER.get_section("profile.txt") \
                == self.collapsed_text:
            _recorder.remove_bundle_section("profile.txt")
            if self is not PROFILER and PROFILER.running:
                _recorder.add_bundle_section("profile.txt",
                                             PROFILER.collapsed_text)

    def clear(self):
        """Drop accumulated counts (test isolation / fresh window)."""
        with self._lock:
            self._counts.clear()
            self._samples = 0
        self._last_leaf = {}
        self._last_total = 0

    # -- sampling ----------------------------------------------------------
    def _run(self):
        last_resource = last_export = 0.0
        while not self._stop.wait(1.0 / max(self.hz, 0.1)):
            try:
                self._sample_once()
            except Exception as e:
                # a broken sampler must not die silently NOR spam: the
                # first few failures leave a trace, the rest count
                self._errors += 1
                if self._errors <= 3:
                    _events.emit("prof_sample_error", error=repr(e))
            now = time.monotonic()
            if now - last_resource >= self.resource_s:
                last_resource = now
                try:
                    _resources.sample()
                except Exception as e:
                    self._errors += 1
                    if self._errors <= 3:
                        _events.emit("prof_resource_error", error=repr(e))
            if now - last_export >= _EXPORT_EVERY_S:
                last_export = now
                try:
                    self._export_top()
                except Exception as e:
                    self._errors += 1
                    if self._errors <= 3:
                        _events.emit("prof_export_error", error=repr(e))

    def _sample_once(self):
        frames = sys._current_frames()
        names = {}
        for t in threading.enumerate():
            if t.ident is not None:
                names[t.ident] = t.name
        own = threading.get_ident()
        walked = []
        for ident, frame in frames.items():
            if ident == own:
                continue            # never profile the profiler
            stack = []
            f = frame
            while f is not None and len(stack) < self.max_depth:
                code = f.f_code
                stack.append(f"{code.co_name} "
                             f"({os.path.basename(code.co_filename)})")
                f = f.f_back
            stack.reverse()         # root first, leaf last (collapsed)
            walked.append((names.get(ident, f"thread-{ident}"),
                           tuple(stack)))
        overflow = 0
        with self._lock:
            self._samples += 1
            for tname, stack in walked:
                key = (tname, stack)
                cur = self._counts.get(key)
                if cur is None and len(self._counts) >= self.max_stacks:
                    overflow += 1
                    key = (tname, ("(stack-table-full)",))
                    cur = self._counts.get(key)
                self._counts[key] = (cur or 0) + 1
            n_stacks = len(self._counts)
        self._c_samples.inc()
        self._g_stacks.set(n_stacks)
        if overflow:
            self._c_overflow.inc(overflow)

    def _export_top(self):
        """Refresh the top-frames gauge from the RECENT window — the
        delta of leaf-self counts since the previous export — so a
        long-lived process's 'right now' signal tracks the current
        hot path instead of converging to the lifetime average (the
        cumulative view stays available at /profile)."""
        counts, _ = self._snapshot_counts()
        leaf, total = {}, 0
        for (tname, stack), c in counts.items():
            total += c
            lf = stack[-1] if stack else "(no stack)"
            leaf[lf] = leaf.get(lf, 0) + c
        win = {f: c - self._last_leaf.get(f, 0) for f, c in leaf.items()}
        win_total = total - self._last_total
        self._last_leaf, self._last_total = leaf, total
        if win_total <= 0:
            return              # idle window (or counts cleared)
        top = sorted(win.items(), key=lambda kv: -kv[1])[:_TOP_GAUGE_N]
        seen = set()
        for frame, c in top:
            if c <= 0:
                continue
            seen.add(frame)
            self._g_top.labels(frame=frame).set(round(c / win_total, 4))
        for frame in self._exported - seen:
            self._g_top.labels(frame=frame).set(0.0)
        self._exported = seen

    # -- read side ---------------------------------------------------------
    def _snapshot_counts(self):
        with self._lock:
            return dict(self._counts), self._samples

    def collapsed_text(self):
        """The folded-stack dump: ``thread;frame;...;leaf count`` lines,
        hottest stack first — paste into any flamegraph renderer."""
        counts, samples = self._snapshot_counts()
        lines = [f"# mxnet_tpu continuous profile: {samples} samples "
                 f"@ {self.hz:g} Hz, {len(counts)} stacks, "
                 f"pid {os.getpid()}"]
        for (tname, stack), n in sorted(counts.items(),
                                        key=lambda kv: -kv[1]):
            lines.append(";".join((tname,) + stack) + f" {n}")
        return "\n".join(lines) + "\n"

    def top_self(self, n=20):
        """Top frames by SELF samples (the leaf of each sampled stack
        — where the interpreter actually was), with the thread-sample
        fraction each represents."""
        counts, _ = self._snapshot_counts()
        self_counts, total = {}, 0
        for (tname, stack), c in counts.items():
            total += c
            leaf = stack[-1] if stack else "(no stack)"
            self_counts[leaf] = self_counts.get(leaf, 0) + c
        out = []
        for frame, c in sorted(self_counts.items(),
                               key=lambda kv: -kv[1])[:n]:
            out.append({"frame": frame, "self": c,
                        "self_frac": round(c / total, 4) if total else 0.0})
        return out

    def snapshot(self, top=20):
        """The ``/profile?format=json`` payload."""
        counts, samples = self._snapshot_counts()
        up = (time.monotonic() - self._started_mono
              if self._started_mono is not None else None)
        return {"running": self.running, "hz": self.hz,
                "samples": samples,
                "uptime_s": round(up, 3) if up is not None else None,
                "threads": threading.active_count(),
                "distinct_stacks": len(counts),
                "errors": self._errors,
                "top_self": self.top_self(top)}


#: process-wide profiler the serving stack / bench start on demand
PROFILER = ContinuousProfiler()

_atexit_lock = threading.Lock()
_atexit_registered = False
_live = set()   # every started profiler instance, not just PROFILER:
                # a custom instance left running into interpreter
                # teardown aborts the process the same way


def _register_atexit_stop():
    global _atexit_registered
    with _atexit_lock:
        if _atexit_registered:
            return
        _atexit_registered = True

    def _stop_at_exit():
        with _atexit_lock:
            running = list(_live)
        for prof in running:
            try:
                prof.stop()
            except Exception:
                pass        # exiting anyway; never mask the real exit

    atexit.register(_stop_at_exit)


def ensure_started():
    """Start the process profiler unless ``MXNET_TPU_PROF=0``
    (idempotent; this is the 'always-on' hook every serving
    engine/router and bench leg calls at start). Returns the profiler
    or None when disabled."""
    if not envvars.get("MXNET_TPU_PROF"):
        return None
    return PROFILER.start()


def collapsed_text():
    return PROFILER.collapsed_text()


def top_self(n=20):
    return PROFILER.top_self(n)


def profile_snapshot(top=20):
    return PROFILER.snapshot(top)
