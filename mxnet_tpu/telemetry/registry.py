"""Process-wide metrics registry: Counter / Gauge / Histogram.

The scrape-side half of the observability story (reference lineage:
MXNet Model Server's management-API metrics). One registry per
process (module-level :data:`REGISTRY`, the default everywhere);
subsystems create metric FAMILIES (a name + label names) and bump
label-addressed children on their hot paths.

Cost discipline: a counter bump or histogram observe is one lock
acquisition and a couple of dict/float ops — cheap enough for the
serving dispatch and kvstore RPC paths it instruments (guarded by the
disabled-path microbenchmark in tests/test_telemetry.py). Everything
expensive (sorting, text rendering) happens at scrape/snapshot time on
the scraper's thread. Gauges can be PULL-based (``set_function``) so
an instrumented component pays nothing until someone scrapes.

Everything is thread-safe; children are created on first touch and
live for the process lifetime (Prometheus counters are cumulative by
contract — `serving.ServingStats` windows reset, registry counters
never do; scrapers diff).

Histograms can carry OpenMetrics-style EXEMPLARS: ``observe(v,
exemplar=trace_id)`` remembers the most recent (and, per bucket, the
slowest-seen) ``(value, trace_id, wall_ts)`` triple for the bucket the
observation landed in, rendered as ``# {trace_id="..."} value ts``
after the ``_bucket`` sample line. That is the machine-readable link
from a latency histogram back to a retrievable trace in
``/traces/<id>`` — the SLO engine's alert surface reads them off
:meth:`Histogram._Child.exemplars`.
"""
from __future__ import annotations

import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "DEFAULT_MS_BUCKETS", "escape_label_value",
           "EXEMPLAR_MAX_AGE_S"]

#: a bucket's reigning exemplar decays after this many seconds: the
#: slowest-ever observation would otherwise pin a trace id whose trace
#: the bounded tail-sampling ring evicted long ago — a dead link. Past
#: this age ANY new exemplar-bearing observation takes the slot, so
#: exposition exemplars always point near the present.
EXEMPLAR_MAX_AGE_S = 30.0

# latency bucket boundaries in milliseconds: sub-ms dispatch overhead
# through multi-second compiles on one axis
DEFAULT_MS_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                      500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0)


def escape_label_value(v):
    """Prometheus text-format label-value escaping (backslash, quote,
    newline — in that order, per the exposition spec)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class _Family:
    """A named metric family: children addressed by label-value tuples
    (label NAMES are fixed at creation; values address children)."""

    kind = "untyped"

    def __init__(self, name, help_text="", labelnames=()):
        self.name = name
        self.help = help_text
        self.labelnames = tuple(labelnames)
        self._children = {}
        self._lock = threading.Lock()

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "name, not both")
            values = tuple(kv[n] for n in self.labelnames)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got "
                f"{values}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values,
                                                  self._make_child())
        return child

    def _default_child(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled {self.labelnames}; "
                             "use .labels(...)")
        return self.labels()

    def _label_str(self, values):
        if not values:
            return ""
        pairs = ",".join(f'{n}="{escape_label_value(v)}"'
                         for n, v in zip(self.labelnames, values))
        return "{" + pairs + "}"

    def _sorted_children(self):
        with self._lock:
            return sorted(self._children.items())


class Counter(_Family):
    """Monotone counter family. ``inc`` on the unlabeled family or a
    ``labels(...)`` child."""

    kind = "counter"

    class _Child:
        __slots__ = ("_value", "_lock")

        def __init__(self):
            self._value = 0.0
            self._lock = threading.Lock()

        def inc(self, n=1):
            if n < 0:
                raise ValueError("counters only go up")
            with self._lock:
                self._value += n

        @property
        def value(self):
            return self._value

    def _make_child(self):
        return Counter._Child()

    def inc(self, n=1):
        self._default_child().inc(n)

    @property
    def value(self):
        return self._default_child().value

    def render(self, out):
        for values, child in self._sorted_children():
            out.append(f"{self.name}{self._label_str(values)} "
                       f"{_fmt(child.value)}")

    def snapshot(self):
        return {self._label_str(v): c.value
                for v, c in self._sorted_children()}


class Gauge(_Family):
    """Settable (or pull-function-backed) point-in-time value."""

    kind = "gauge"

    class _Child:
        __slots__ = ("_value", "_fn", "_lock")

        def __init__(self):
            self._value = 0.0
            self._fn = None
            self._lock = threading.Lock()

        def set(self, v):
            with self._lock:
                self._value = float(v)
                self._fn = None

        def inc(self, n=1):
            with self._lock:
                self._value += n

        def dec(self, n=1):
            self.inc(-n)

        def set_function(self, fn):
            """Evaluate ``fn()`` at scrape time (zero hot-path cost)."""
            with self._lock:
                self._fn = fn

        @property
        def value(self):
            fn = self._fn
            if fn is not None:
                try:
                    return float(fn())
                except Exception:
                    return float("nan")
            return self._value

    def _make_child(self):
        return Gauge._Child()

    def set(self, v):
        self._default_child().set(v)

    def inc(self, n=1):
        self._default_child().inc(n)

    def dec(self, n=1):
        self._default_child().dec(n)

    def set_function(self, fn):
        self._default_child().set_function(fn)

    @property
    def value(self):
        return self._default_child().value

    def render(self, out):
        for values, child in self._sorted_children():
            out.append(f"{self.name}{self._label_str(values)} "
                       f"{_fmt(child.value)}")

    def snapshot(self):
        return {self._label_str(v): c.value
                for v, c in self._sorted_children()}


class Histogram(_Family):
    """Fixed-boundary histogram (Prometheus bucket semantics: each
    ``le`` bucket is CUMULATIVE, ``+Inf`` equals ``_count``)."""

    kind = "histogram"

    class _Child:
        __slots__ = ("_bounds", "_counts", "_sum", "_count", "_lock",
                     "_exemplars")

        def __init__(self, bounds):
            self._bounds = bounds
            self._counts = [0] * (len(bounds) + 1)   # last = +Inf
            self._sum = 0.0
            self._count = 0
            self._lock = threading.Lock()
            self._exemplars = None      # allocated on first exemplar

        def observe(self, v, exemplar=None):
            v = float(v)
            i = 0
            bounds = self._bounds
            n = len(bounds)
            # linear scan beats bisect for the ~dozen buckets used here
            while i < n and v > bounds[i]:
                i += 1
            with self._lock:
                self._counts[i] += 1
                self._sum += v
                self._count += 1
                if exemplar is not None:
                    if self._exemplars is None:
                        self._exemplars = [None] * (len(bounds) + 1)
                    prev = self._exemplars[i]
                    # per bucket, the SLOWEST RECENT observation wins:
                    # a firing latency alert wants the worst retrievable
                    # trace in that bucket, not whichever came last —
                    # but a stale champion decays (EXEMPLAR_MAX_AGE_S,
                    # measured on the monotonic clock; the wall ts is
                    # exposition-only) so the id still resolves in the
                    # bounded trace ring
                    mono = time.monotonic()
                    if (prev is None or v >= prev["value"]
                            or mono - prev["mono"] > EXEMPLAR_MAX_AGE_S):
                        self._exemplars[i] = {
                            "trace_id": str(exemplar), "value": v,
                            "ts": round(time.time(), 3), "mono": mono}

        def exemplars(self):
            """``{bucket_bound_or_inf: {trace_id, value, ts}}`` for
            buckets that have one (empty when none were recorded)."""
            with self._lock:
                ex = list(self._exemplars) if self._exemplars else []
            out = {}
            for i, e in enumerate(ex):
                if e is not None:
                    bound = (self._bounds[i] if i < len(self._bounds)
                             else float("inf"))
                    out[bound] = {k: v for k, v in e.items()
                                  if k != "mono"}
            return out

        @property
        def count(self):
            return self._count

        @property
        def sum(self):
            return self._sum

        def cumulative(self):
            with self._lock:
                counts = list(self._counts)
            acc, out = 0, []
            for c in counts:
                acc += c
                out.append(acc)
            return out

    def __init__(self, name, help_text="", labelnames=(), buckets=None):
        super().__init__(name, help_text, labelnames)
        bounds = tuple(float(b) for b in (buckets or DEFAULT_MS_BUCKETS))
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(f"histogram buckets must ascend: {bounds}")
        self.buckets = bounds

    def _make_child(self):
        return Histogram._Child(self.buckets)

    def observe(self, v, exemplar=None):
        self._default_child().observe(v, exemplar=exemplar)

    @property
    def count(self):
        return self._default_child().count

    @property
    def sum(self):
        return self._default_child().sum

    def exemplars(self):
        return self._default_child().exemplars()

    def render(self, out):
        for values, child in self._sorted_children():
            cum = child.cumulative()
            exemplars = child.exemplars()
            for bound, acc in zip(self.buckets + (float("inf"),),
                                  cum):
                lv = values + (("+Inf" if bound == float("inf")
                                else _fmt(bound)),)
                pairs = ",".join(
                    f'{n}="{escape_label_value(v)}"'
                    for n, v in zip(self.labelnames + ("le",), lv))
                line = f"{self.name}_bucket{{{pairs}}} {acc}"
                ex = exemplars.get(bound)
                if ex is not None:
                    # OpenMetrics exemplar syntax on the bucket line:
                    # the trace id a scraper can resolve at /traces/<id>
                    line += (f' # {{trace_id="'
                             f'{escape_label_value(ex["trace_id"])}"}} '
                             f'{_fmt(ex["value"])} {_fmt(ex["ts"])}')
                out.append(line)
            ls = self._label_str(values)
            out.append(f"{self.name}_sum{ls} {_fmt(child.sum)}")
            out.append(f"{self.name}_count{ls} {child.count}")

    def snapshot(self):
        return {self._label_str(v): {"count": c.count,
                                     "sum": round(c.sum, 3)}
                for v, c in self._sorted_children()}


def _fmt(v):
    """Render a float the Prometheus way: integers without the dot."""
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class MetricsRegistry:
    """Create-or-get metric families by name; render/snapshot them.

    ``counter/gauge/histogram`` are idempotent: the same name returns
    the SAME family (so `ServingStats` instances recreated by
    ``reset_stats`` keep feeding one cumulative counter set), and a
    name re-registered as a different kind or label set raises.
    """

    def __init__(self):
        self._metrics = {}
        self._lock = threading.Lock()

    def _get_or_make(self, cls, name, help_text, labelnames, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(m).__name__}{m.labelnames}")
                want = kw.get("buckets")
                if want is not None and m.buckets != tuple(
                        float(b) for b in want):
                    # silently handing back a family with DIFFERENT
                    # boundaries would mis-bucket the second caller's
                    # observations — as loud as a kind conflict
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}")
                return m
            m = cls(name, help_text, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help_text="", labelnames=()):
        return self._get_or_make(Counter, name, help_text, labelnames)

    def gauge(self, name, help_text="", labelnames=()):
        return self._get_or_make(Gauge, name, help_text, labelnames)

    def histogram(self, name, help_text="", labelnames=(), buckets=None):
        return self._get_or_make(Histogram, name, help_text, labelnames,
                                 buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def render_prometheus(self):
        """The full text exposition (format 0.0.4), families sorted by
        name, children sorted by label values — deterministic output
        for goldens and diff-based scrapers."""
        out = []
        with self._lock:
            families = sorted(self._metrics.items())
        for name, fam in families:
            if fam.help:
                out.append(f"# HELP {name} "
                           + fam.help.replace("\\", "\\\\")
                           .replace("\n", "\\n"))
            out.append(f"# TYPE {name} {fam.kind}")
            fam.render(out)
        return "\n".join(out) + "\n"

    def snapshot(self):
        """JSON-able {name: {kind, values}} dump (the /stats analog of
        /metrics)."""
        with self._lock:
            families = sorted(self._metrics.items())
        return {name: {"kind": fam.kind, "values": fam.snapshot()}
                for name, fam in families}

    def snapshot_compact(self):
        """Nonzero counters + histogram counts only — small enough to
        embed per bench leg in `bench_suite_summary`."""
        out = {}
        with self._lock:
            families = sorted(self._metrics.items())
        for name, fam in families:
            if fam.kind == "counter":
                vals = {k or "": v for k, v in fam.snapshot().items() if v}
                if vals:
                    out[name] = vals
            elif fam.kind == "histogram":
                vals = {k or "": v["count"]
                        for k, v in fam.snapshot().items() if v["count"]}
                if vals:
                    out[name] = vals
        return out


#: the process-wide default registry every subsystem instruments
REGISTRY = MetricsRegistry()
