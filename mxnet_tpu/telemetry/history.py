"""Retrospective fleet history: a bounded on-disk time-series store.

Every other observability surface answers *what is happening now*:
``/metrics`` is an instantaneous scrape, the SLO evaluator's
:class:`~.slo.SampleStore` is an in-memory window that dies with the
process, and flight bundles snapshot the moment of failure. The first
postmortem question — *what did the fleet look like for the ten
minutes before the page?* — needs history, so this module keeps one:

- :class:`HistoryStore` — a stdlib-only time-series store: one sorted
  ``(t, value)`` list per series key (``family{labels}``), kept in
  three downsampling tiers (``raw`` → ``10s`` → ``60s``, each bucket
  keeping the LAST cumulative sample so windowed rates stay exact),
  bounded by per-tier retention and a per-series point cap. With
  ``MXNET_TPU_HISTORY_DIR`` set, every tier also appends to
  crash-safe segment files — plain JSONL, one chain per family and
  tier, rotated by size (``MXNET_TPU_HISTORY_SEGMENT_MB``), swept by
  retention and the ``MXNET_TPU_HISTORY_MAX_MB`` budget, and reloaded
  on the next construction (a torn final line from a hard kill is
  skipped and counted, never raised);
- :class:`HistoryScraper` — the feeding daemon: engines sample the
  process registry, routers their fleet-merged exposition, every
  ``MXNET_TPU_HISTORY_SCRAPE_S`` seconds, keeping the families named
  by the :data:`DEFAULT_RULES` recording rules (mxlint cross-checks
  those names against declared families, like dashboards);
- range queries — :meth:`HistoryStore.query_range` evaluates
  ``value`` / ``rate()`` / ``increase()`` / quantile-over-time on the
  stored series over a start/end/step grid; ``expo.TelemetryServer``
  serves it at ``/query_range`` (and the key listing at ``/series``);
- incident forensics — when the :mod:`.incidents` tracker opens an
  incident it calls :func:`on_incident_open`; every live scraper
  freezes its preceding window (series + the owner's SLO objective
  table and alert-rule describes) and the flight bundle's
  ``history_<owner>.json`` section carries the frozen windows —
  exactly what :func:`~.slo.replay_history` re-judges after the fact.

``MXNET_TPU_HISTORY=0`` disables the subsystem: no store, no thread,
no endpoints. Timestamps are wall-clock (``time.time()``): history
outlives processes and is merged across machines, so wall ordering —
the events log's convention — is the honest axis.
"""
from __future__ import annotations

import bisect
import json
import os
import re
import threading
import time
from collections import deque

from .. import envvars
from . import events as _events
from . import recorder as _recorder
from .expo import histogram_quantile, parse_labels, parse_prometheus_text
from .registry import REGISTRY

__all__ = ["RecordingRule", "DEFAULT_RULES", "HistoryStore",
           "HistoryScraper", "default_store", "scrapers",
           "on_incident_open"]

#: tier spec: (label, bucket resolution seconds; 0 = raw)
_TIER_RES = (("raw", 0.0), ("10s", 10.0), ("60s", 60.0))

#: per-series point cap per tier (older half coarsened past it, like
#: the SLO SampleStore — range queries need anchors, not every tick)
_MAX_POINTS = 4096

_SUFFIX = re.compile(r"_(bucket|sum|count)$")


def family_of(key):
    """Series key → base family name (histogram suffixes stripped)."""
    return _SUFFIX.sub("", key.split("{", 1)[0])


class RecordingRule:
    """One named recording rule in the history config: capture this
    family into the store, evaluated later as ``kind`` (``counter``
    families answer rate/increase, ``gauge`` value-over-time,
    ``histogram`` quantile-over-time). mxlint's telemetry-consistency
    pass cross-checks every rule's family against the declared
    families — a rule over a renamed family would record nothing and
    every retro query over it would come back empty."""

    __slots__ = ("name", "family", "kind")

    def __init__(self, name, family, kind="counter"):
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown recording-rule kind {kind!r}")
        self.name = str(name)
        self.family = str(family)
        self.kind = kind

    def row(self):
        return {"name": self.name, "family": self.family,
                "kind": self.kind}


#: the default history config: the headline families a postmortem (or
#: mxtop) asks about. Kept deliberately curated — history is bounded,
#: and every family here is one mxlint cross-checks against the
#: declared set.
DEFAULT_RULES = (
    RecordingRule("serving_requests",
                  family="mxnet_tpu_serving_requests_total"),
    RecordingRule("serving_latency",
                  family="mxnet_tpu_serving_latency_ms",
                  kind="histogram"),
    RecordingRule("inter_token_latency",
                  family="mxnet_tpu_serving_inter_token_latency_ms",
                  kind="histogram"),
    RecordingRule("decode_tokens",
                  family="mxnet_tpu_serving_decode_tokens_total"),
    RecordingRule("cost_seconds",
                  family="mxnet_tpu_serving_cost_seconds_total"),
    RecordingRule("cost_tokens",
                  family="mxnet_tpu_serving_cost_tokens_total"),
    RecordingRule("queue_depth",
                  family="mxnet_tpu_serving_queue_depth", kind="gauge"),
    RecordingRule("kv_pages",
                  family="mxnet_tpu_serving_kv_pages", kind="gauge"),
    RecordingRule("tenant_requests",
                  family="mxnet_tpu_serving_tenant_requests_total"),
    RecordingRule("tenant_latency",
                  family="mxnet_tpu_serving_tenant_latency_ms",
                  kind="histogram"),
    RecordingRule("tenant_cost_seconds",
                  family="mxnet_tpu_serving_tenant_cost_seconds_total"),
    RecordingRule("tenant_tokens",
                  family="mxnet_tpu_serving_tenant_tokens_total"),
    RecordingRule("router_requests",
                  family="mxnet_tpu_router_requests_total"),
    RecordingRule("router_latency",
                  family="mxnet_tpu_router_latency_ms",
                  kind="histogram"),
    RecordingRule("router_engine_up",
                  family="mxnet_tpu_router_engine_up", kind="gauge"),
    RecordingRule("canary_requests",
                  family="mxnet_tpu_canary_requests_total"),
    RecordingRule("canary_latency_ema",
                  family="mxnet_tpu_canary_latency_ema_ms",
                  kind="gauge"),
    RecordingRule("autoscaler_seats",
                  family="mxnet_tpu_autoscaler_seats", kind="gauge"),
    RecordingRule("autoscaler_model_seats",
                  family="mxnet_tpu_autoscaler_model_seats",
                  kind="gauge"),
    RecordingRule("alerts_firing",
                  family="mxnet_tpu_alerts_firing", kind="gauge"),
    RecordingRule("slo_burn_rate",
                  family="mxnet_tpu_slo_burn_rate", kind="gauge"),
    RecordingRule("slo_error_budget",
                  family="mxnet_tpu_slo_error_budget_remaining",
                  kind="gauge"),
    RecordingRule("incidents_open",
                  family="mxnet_tpu_incidents_open", kind="gauge"),
    RecordingRule("stage_latency",
                  family="mxnet_tpu_serving_stage_latency_ms",
                  kind="histogram"),
    RecordingRule("stage_seconds",
                  family="mxnet_tpu_serving_stage_seconds_total"),
)


class _Tier:
    """One downsampling tier: ``{key: [(t, v), ...]}`` bounded by
    retention + a per-series point cap. ``resolution_s > 0`` buckets
    incoming samples on absolute boundaries and keeps each bucket's
    LAST sample (cumulative counters diff exactly across bucket
    edges; gauges keep their freshest reading)."""

    __slots__ = ("label", "resolution_s", "retain_s", "series",
                 "pending")

    def __init__(self, label, resolution_s, retain_s):
        self.label = label
        self.resolution_s = float(resolution_s)
        self.retain_s = float(retain_s)
        self.series = {}        # key -> [(t, v), ...] sorted by t
        self.pending = {}       # key -> [bucket_idx, t, v]

    def add(self, key, t, v):
        """Feed one sample; returns the (t, v) flushed into this tier
        (None while the sample stays pending inside its bucket)."""
        if self.resolution_s <= 0:
            self._store(key, t, v)
            return (t, v)
        idx = int(t // self.resolution_s)
        pend = self.pending.get(key)
        out = None
        if pend is not None and pend[0] != idx:
            # bucket closed: flush its last sample at the bucket edge
            out = ((pend[0] + 1) * self.resolution_s, pend[2])
            self._store(key, out[0], out[1])
        self.pending[key] = [idx, t, v]
        return out

    def _store(self, key, t, v):
        arr = self.series.get(key)
        if arr is None:
            arr = self.series.setdefault(key, [])
        arr.append((t, v))
        horizon = t - self.retain_s
        if len(arr) > 2 and arr[1][0] < horizon:
            # keep ONE pre-horizon anchor so a full-width window can
            # still diff against something
            i = bisect.bisect_left(arr, (horizon, -1e308)) - 1
            if i > 0:
                del arr[:i]
        if len(arr) > _MAX_POINTS:
            half = len(arr) // 2
            arr[:half] = arr[0:half:2]


class HistoryStore:
    """Bounded multi-tier time-series store, optionally disk-backed.

    Parameters
    ----------
    dirpath : persist segments under this directory (default
        ``MXNET_TPU_HISTORY_DIR``); None keeps the store memory-only
        with the same bounds. Existing segments are reloaded (torn
        final lines skipped and counted in ``load_skipped``).
    retain_s : retention of the coarsest (60 s) tier (default
        ``MXNET_TPU_HISTORY_RETAIN_S``); the raw and 10 s tiers
        retain ``min(retain_s, 900)`` / ``min(retain_s, 10800)``.
    max_mb / segment_mb : on-disk budget and segment rotation size
        (``MXNET_TPU_HISTORY_MAX_MB`` / ``_SEGMENT_MB``).
    """

    def __init__(self, dirpath=None, retain_s=None, max_mb=None,
                 segment_mb=None, now=None):
        self.dir = (dirpath if dirpath is not None
                    else envvars.get("MXNET_TPU_HISTORY_DIR"))
        retain = (float(retain_s) if retain_s is not None
                  else envvars.get("MXNET_TPU_HISTORY_RETAIN_S"))
        self.retain_s = max(1.0, retain)
        self.max_bytes = (float(max_mb) if max_mb is not None
                          else envvars.get("MXNET_TPU_HISTORY_MAX_MB")
                          ) * 1024 * 1024
        self.segment_bytes = max(
            4096.0,
            (float(segment_mb) if segment_mb is not None
             else envvars.get("MXNET_TPU_HISTORY_SEGMENT_MB"))
            * 1024 * 1024)
        self._lock = threading.Lock()
        self.tiers = tuple(
            _Tier(label, res, self._tier_retain(label))
            for label, res in _TIER_RES)
        self._files = {}        # (family, tier) -> [fh, path, size]
        self._seq = {}          # (family, tier) -> next segment seq
        self.load_skipped = 0
        self.appended = 0
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)
            self._load(time.time() if now is None else now)

    def _tier_retain(self, label):
        if label == "raw":
            return min(self.retain_s, 900.0)
        if label == "10s":
            return min(self.retain_s, 10800.0)
        return self.retain_s

    # -- write path ---------------------------------------------------------
    def append(self, t, samples):
        """Record one scrape: ``samples`` is ``{series_key: float}``
        (the shape :func:`~.expo.parse_prometheus_text` returns)."""
        t = float(t)
        with self._lock:
            rotated = False
            for tier in self.tiers:
                flushed = {}    # family -> {key: (t, v)}
                for key, v in samples.items():
                    out = tier.add(key, t, float(v))
                    if out is not None:
                        flushed.setdefault(family_of(key), {})[key] = out
                if self.dir:
                    for fam, entries in sorted(flushed.items()):
                        # one line per (family, flush time): within a
                        # tier every series flushed by THIS scrape
                        # shares the bucket edge (absolute alignment)
                        by_t = {}
                        for key, (ft, fv) in entries.items():
                            by_t.setdefault(ft, {})[key] = fv
                        for ft in sorted(by_t):
                            rotated |= self._write(fam, tier.label,
                                                   ft, by_t[ft])
            self.appended += 1
            if rotated:
                self._enforce_disk(t)

    def _write(self, family, tier, t, keyvals):
        rec = {"t": round(t, 3),
               "s": {k: v for k, v in sorted(keyvals.items())}}
        line = json.dumps(rec, separators=(",", ":")) + "\n"
        slot = self._files.get((family, tier))
        if slot is None:
            slot = self._open_segment(family, tier)
        fh, path, size = slot
        try:
            fh.write(line)
            fh.flush()
        except OSError:
            return False        # disk trouble must not stop sampling
        slot[2] = size + len(line)
        if slot[2] >= self.segment_bytes:
            try:
                fh.close()
            except OSError:
                pass
            del self._files[(family, tier)]
            return True
        return False

    def _fam_dir(self, family):
        return os.path.join(self.dir, family)

    def _open_segment(self, family, tier):
        d = self._fam_dir(family)
        os.makedirs(d, exist_ok=True)
        seq = self._seq.get((family, tier))
        if seq is None:
            seq = 1 + max(
                [self._seg_seq(p) for p in os.listdir(d)
                 if p.startswith(f"{tier}-")] or [0])
        self._seq[(family, tier)] = seq + 1
        path = os.path.join(d, f"{tier}-{seq:08d}.seg")
        fh = open(path, "a", encoding="utf-8")
        slot = [fh, path, 0]
        self._files[(family, tier)] = slot
        return slot

    @staticmethod
    def _seg_seq(name):
        m = re.match(r"[a-z0-9]+-(\d+)\.seg$", name)
        return int(m.group(1)) if m else 0

    def _segments(self):
        """Every segment file on disk: ``[(mtime, size, path,
        tier_label), ...]``."""
        out = []
        try:
            fams = os.listdir(self.dir)
        except OSError:
            return out
        for fam in fams:
            d = self._fam_dir(fam)
            if not os.path.isdir(d):
                continue
            for name in os.listdir(d):
                if not name.endswith(".seg"):
                    continue
                path = os.path.join(d, name)
                try:
                    st = os.stat(path)
                except OSError:
                    continue
                out.append((st.st_mtime, st.st_size, path,
                            name.split("-", 1)[0]))
        return out

    def _enforce_disk(self, now):
        """Retention + budget sweep over SEALED segments (the active
        handles keep writing; a fresh segment is never deleted)."""
        active = {slot[1] for slot in self._files.values()}
        retain = {t.label: t.retain_s for t in self.tiers}
        segs = [s for s in self._segments() if s[2] not in active]
        kept = []
        for mtime, size, path, tier in segs:
            if now - mtime > retain.get(tier, self.retain_s):
                self._unlink(path)
            else:
                kept.append((mtime, size, path, tier))
        total = sum(s[1] for s in kept) \
            + sum(slot[2] for slot in self._files.values())
        # over budget: drop the oldest sealed segments first (raw
        # rotates fastest, so the finest tier naturally goes first)
        for mtime, size, path, tier in sorted(kept):
            if total <= self.max_bytes:
                break
            self._unlink(path)
            total -= size

    @staticmethod
    def _unlink(path):
        try:
            os.unlink(path)
            d = os.path.dirname(path)
            if not os.listdir(d):
                os.rmdir(d)
        except OSError:
            pass

    def _load(self, now):
        """Reload persisted segments into the memory tiers (crash
        recovery: a torn final line after a hard kill is skipped and
        counted, never raised — the postmortem reads on)."""
        tiers = {t.label: t for t in self.tiers}
        for fam in sorted(os.listdir(self.dir)):
            d = self._fam_dir(fam)
            if not os.path.isdir(d):
                continue
            by_tier = {}
            for name in sorted(os.listdir(d)):
                if name.endswith(".seg"):
                    by_tier.setdefault(name.split("-", 1)[0],
                                       []).append(name)
            for label, names in by_tier.items():
                tier = tiers.get(label)
                if tier is None:
                    continue
                self._seq[(fam, label)] = 1 + max(
                    self._seg_seq(n) for n in names)
                for name in sorted(names, key=self._seg_seq):
                    self._load_segment(tier, os.path.join(d, name), now)
        for tier in self.tiers:
            for arr in tier.series.values():
                arr.sort()

    def _load_segment(self, tier, path, now):
        try:
            fh = open(path, encoding="utf-8", errors="replace")
        except OSError:
            return
        with fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    t = float(rec["t"])
                    samples = rec["s"]
                except (ValueError, KeyError, TypeError):
                    self.load_skipped += 1   # torn/corrupt: count, go on
                    continue
                if now - t > tier.retain_s:
                    continue
                for key, v in samples.items():
                    try:
                        tier._store(key, t, float(v))
                    except (TypeError, ValueError):
                        self.load_skipped += 1

    def close(self):
        """Seal the active segments (flush + close). The store stays
        queryable from memory."""
        with self._lock:
            for slot in self._files.values():
                try:
                    slot[0].close()
                except OSError:
                    pass
            self._files.clear()

    # -- read path ----------------------------------------------------------
    def keys(self):
        with self._lock:
            out = set()
            for tier in self.tiers:
                out.update(tier.series)
            return sorted(out)

    def _combined_locked(self, key):
        """One stitched series per key: coarse history where only the
        coarse tiers still cover, the finest available after that."""
        raw = self.tiers[0].series.get(key, [])
        t10 = self.tiers[1].series.get(key, [])
        t60 = self.tiers[2].series.get(key, [])
        raw_start = raw[0][0] if raw else float("inf")
        t10_start = t10[0][0] if t10 else float("inf")
        out = [p for p in t60 if p[0] < min(t10_start, raw_start)]
        out += [p for p in t10 if p[0] < raw_start]
        out += raw
        return out

    def points(self, key, start=None, end=None):
        with self._lock:
            arr = self._combined_locked(key)
        if start is not None:
            i = bisect.bisect_left(arr, (float(start), -1e308))
            # keep one anchor before the range for rate windows
            arr = arr[max(0, i - 1):]
        if end is not None:
            arr = arr[:bisect.bisect_right(arr, (float(end), 1e308))]
        return arr

    def series(self):
        """The ``/series`` body: every stored key with its parsed
        labels, tier point counts and covered time range."""
        with self._lock:
            keys = set()
            for tier in self.tiers:
                keys.update(tier.series)
            rows = []
            for key in sorted(keys):
                name, labels = parse_labels(key)
                comb = self._combined_locked(key)
                rows.append({
                    "key": key, "name": name,
                    "family": family_of(key), "labels": labels,
                    "tiers": {t.label: len(t.series.get(key, ()))
                              for t in self.tiers},
                    "first_t": round(comb[0][0], 3) if comb else None,
                    "last_t": round(comb[-1][0], 3) if comb else None,
                    "points": len(comb)})
        return {"series": rows, "count": len(rows),
                "appended": self.appended,
                "load_skipped": self.load_skipped,
                "dir": self.dir,
                "retain_s": self.retain_s}

    # -- range evaluation ---------------------------------------------------
    @staticmethod
    def _value_at(arr, t, staleness):
        i = bisect.bisect_right(arr, (t, 1e308)) - 1
        if i < 0:
            return None
        pt, pv = arr[i]
        if t - pt > staleness:
            return None
        return pv

    @staticmethod
    def _increase(arr, t, window):
        """Counter increase over ``(t - window, t]``: sum of positive
        deltas, counter resets (a restarted process) re-anchored at
        zero — partial coverage uses the oldest in-window anchor."""
        i1 = bisect.bisect_right(arr, (t, 1e308)) - 1
        if i1 < 1:
            return None, 0.0
        cut = t - window
        i0 = bisect.bisect_right(arr, (cut, 1e308)) - 1
        if i0 < 0:
            i0 = 0
        if i1 <= i0:
            return None, 0.0
        acc = 0.0
        prev = arr[i0][1]
        for j in range(i0 + 1, i1 + 1):
            v = arr[j][1]
            acc += (v - prev) if v >= prev else v
            prev = v
        span = arr[i1][0] - arr[i0][0]
        return acc, span

    def query_range(self, name, start=None, end=None, step=None,
                    fn="value", q=None, window=None, match=None,
                    now=None, max_points=2001):
        """Evaluate one family over a time grid.

        ``fn``: ``value`` (step-function sample), ``rate`` /
        ``increase`` (reset-aware, over ``window`` trailing seconds,
        default = ``step``), or ``quantile`` (quantile-over-time on a
        histogram's ``_bucket`` series: windowed increase per bucket,
        then the PromQL interpolation; ``q`` is the percentile,
        e.g. 99). Returns the grid and one row per matching series
        (``null`` where a point can't be evaluated)."""
        now = time.time() if now is None else float(now)
        end = now if end is None else float(end)
        start = end - 300.0 if start is None else float(start)
        if end < start:
            start, end = end, start
        step = float(step) if step else max(1.0, (end - start) / 240.0)
        step = max(1e-3, step)
        n = int((end - start) / step) + 1
        if n > max_points:
            step = (end - start) / (max_points - 1)
            n = max_points
        grid = [start + i * step for i in range(n)]
        w = float(window) if window else max(step, 1e-3)
        match = match or {}
        name = str(name)
        want = name + "_bucket" if fn == "quantile" else name
        rows = []
        with self._lock:
            keys = set()
            for tier in self.tiers:
                keys.update(tier.series)
            selected = {}
            for key in sorted(keys):
                kname, labels = parse_labels(key)
                if kname != want:
                    continue
                if any(labels.get(k) != str(v)
                       for k, v in match.items() if k != "le"):
                    continue
                selected[key] = (labels,
                                 self._combined_locked(key))
        if fn == "quantile":
            rows = self._quantile_rows(name, selected, grid, w, q)
        else:
            staleness = max(2.0 * step, w, 60.0)
            for key, (labels, arr) in selected.items():
                pts = []
                for t in grid:
                    if fn == "value":
                        v = self._value_at(arr, t, staleness)
                    else:
                        inc, span = self._increase(arr, t, w)
                        if inc is None:
                            v = None
                        elif fn == "rate":
                            v = inc / span if span > 0 else None
                        else:
                            v = inc
                    pts.append([round(t, 3),
                                None if v is None else round(v, 6)])
                rows.append({"key": key, "labels": labels,
                             "points": pts})
        return {"name": name, "fn": fn, "q": q,
                "start": round(start, 3), "end": round(end, 3),
                "step": round(step, 3), "window_s": round(w, 3),
                "series": rows}

    def _quantile_rows(self, name, selected, grid, w, q):
        """Quantile-over-time: group bucket series by their non-``le``
        labels; per grid point take each bucket's windowed increase
        and interpolate the quantile over the resulting (cumulative)
        bucket counts."""
        q = 99.0 if q is None else float(q)
        groups = {}
        for key, (labels, arr) in selected.items():
            if "le" not in labels:
                continue
            gkey = tuple(sorted((k, v) for k, v in labels.items()
                                if k != "le"))
            groups.setdefault(gkey, []).append((labels["le"], arr))
        rows = []
        for gkey, buckets in sorted(groups.items()):
            pts = []
            for t in grid:
                parsed = {}
                for le, arr in buckets:
                    inc, _ = self._increase(arr, t, w)
                    if inc is not None:
                        parsed[f'{name}_bucket{{le="{le}"}}'] = inc
                v = histogram_quantile(parsed, name, q) \
                    if parsed else None
                pts.append([round(t, 3),
                            None if v is None else round(v, 6)])
            rows.append({"key": f"{name}_bucket", "labels": dict(gkey),
                         "points": pts})
        return rows

    def forensics(self, window_s=None, end=None):
        """Freeze the trailing window: ``{key: [[t, v], ...]}`` for
        every stored series — the raw material
        :func:`~.slo.replay_history` re-judges. Bounded by the raw
        tier's retention (coarser tiers fill in where raw has aged
        out)."""
        end = time.time() if end is None else float(end)
        window_s = (float(window_s) if window_s is not None
                    else self.tiers[0].retain_s)
        start = end - window_s
        series = {}
        for key in self.keys():
            pts = self.points(key, start=start, end=end)
            if pts:
                series[key] = [[round(t, 3), v] for t, v in pts]
        return {"start": round(start, 3), "end": round(end, 3),
                "window_s": round(window_s, 3), "series": series}


# -- the feeding daemon ------------------------------------------------------

_REG_LOCK = threading.Lock()
_SCRAPERS = []


def scrapers():
    """The live scrapers in this process (engine + router each run
    one; the incident hook freezes them all)."""
    with _REG_LOCK:
        return list(_SCRAPERS)


def default_store():
    """The first live scraper's store — what an exposition server
    without an explicit ``history_fn`` serves (None = 404)."""
    with _REG_LOCK:
        return _SCRAPERS[0].store if _SCRAPERS else None


def on_incident_open(incident_id):
    """Incident-path hook (called by :class:`~.incidents.
    IncidentTracker` the moment an incident opens): every live
    scraper freezes its PRECEDING window now, so the flight bundle —
    written later, after the failure developed — still carries what
    the fleet looked like before."""
    for s in scrapers():
        try:
            s.freeze(incident_id)
        except Exception:
            pass                # forensics must not hurt the tracker


class HistoryScraper:
    """Samples an exposition into a :class:`HistoryStore` on a daemon
    thread. Engines pass nothing (the process registry is sampled);
    routers pass ``text_fn=self.metrics_text`` so history records the
    fleet-MERGED view. ``slo_fn``/``alerts_fn`` (the owner's snapshot
    callables) ride into every freeze so retro replay has the
    objective table and rule describes next to the series."""

    def __init__(self, owner_id, store=None, registry=None,
                 text_fn=None, interval_s=None, rules=None,
                 extra_families=(), slo_fn=None, alerts_fn=None,
                 freeze_window_s=None):
        self.owner_id = str(owner_id)
        self.store = store if store is not None else HistoryStore()
        self._registry = registry if registry is not None else REGISTRY
        self._text_fn = text_fn
        self.interval_s = (float(interval_s) if interval_s is not None
                           else envvars.get("MXNET_TPU_HISTORY_SCRAPE_S"))
        self.rules = tuple(rules if rules is not None else DEFAULT_RULES)
        self._families = {r.family for r in self.rules}
        self._families.update(str(f) for f in extra_families)
        self._slo_fn = slo_fn
        self._alerts_fn = alerts_fn
        self._freeze_window_s = freeze_window_s
        self._freezes = deque(maxlen=4)   # (incident_id, forensics)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._section = f"history_{self.owner_id}"
        self.scrapes = 0
        self.errors = 0

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name=f"mxnet_tpu_history_{self.owner_id}")
            self._thread.start()
        with _REG_LOCK:
            if self not in _SCRAPERS:
                _SCRAPERS.append(self)
        _recorder.add_bundle_section(self._section, self.bundle_section)
        return self

    def stop(self):
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        _recorder.remove_bundle_section(self._section)
        with _REG_LOCK:
            if self in _SCRAPERS:
                _SCRAPERS.remove(self)
        self.store.close()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception as e:
                self.errors += 1
                _events.emit("history_scrape_error",
                             owner=self.owner_id, error=repr(e))

    # -- sampling -----------------------------------------------------------
    def _keep(self, key):
        return family_of(key) in self._families

    def scrape_once(self, now=None):
        """One sample: render (or fetch) the exposition, keep the
        configured families, append. Returns the kept series count."""
        text = (self._text_fn() if self._text_fn is not None
                else self._registry.render_prometheus())
        parsed = parse_prometheus_text(text)
        kept = {k: v for k, v in parsed.items() if self._keep(k)}
        self.store.append(time.time() if now is None else float(now),
                          kept)
        self.scrapes += 1
        return len(kept)

    # -- forensics ----------------------------------------------------------
    def forensics(self, window_s=None):
        """The freeze payload: the trailing series window plus the
        owner's live objective table and alert-rule describes (what
        retro replay needs to re-judge the page)."""
        out = self.store.forensics(
            window_s=(window_s if window_s is not None
                      else self._freeze_window_s))
        out.update(owner=self.owner_id,
                   interval_s=self.interval_s,
                   rules=[r.row() for r in self.rules])
        for label, fn in (("objectives", self._slo_fn),
                          ("alerts", self._alerts_fn)):
            if fn is None:
                continue
            try:
                out[label] = fn()
            except Exception as e:
                out[label] = {"error": repr(e)}
        return out

    def freeze(self, incident_id=None):
        """Capture the preceding window NOW (incident open). Kept in a
        small ring; the flight bundle's ``history_<owner>.json``
        section carries it."""
        snap = self.forensics()
        with self._lock:
            self._freezes.append(
                {"incident_id": incident_id, **snap})
        _events.emit("history_freeze", owner=self.owner_id,
                     incident_id=incident_id,
                     series=len(snap.get("series") or ()))
        return snap

    def bundle_section(self):
        """Flight-bundle section: the frozen pre-incident windows
        (or, when nothing froze — e.g. a watchdog bundle with no
        incident — the live trailing window)."""
        with self._lock:
            frozen = list(self._freezes)
        if not frozen:
            frozen = [{"incident_id": None, **self.forensics()}]
        return {"owner": self.owner_id, "freezes": frozen}
