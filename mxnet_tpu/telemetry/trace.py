"""Request-trace propagation: one id, minted once, visible everywhere.

A trace id is a short opaque string minted at a request's front door
(``ServingEngine.submit``; a kvstore RPC mints one per call when none
is active). It rides a :mod:`contextvars` context variable through
queue → batcher → dispatch inside a process, is stamped into
Chrome-trace/xprof spans by ``profiler.Scope``, and crosses the
dist_async wire as a frame field so the worker's and server's event
logs correlate on the same push.

contextvar (not a thread-local): the serving worker adopts the trace
context of the batch it dispatches, and any future async reshuffle of
the worker loop inherits the right ids for free.
"""
from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading

__all__ = ["new_trace_id", "current_trace_id", "set_trace_id",
           "reset_trace_id", "trace_context"]

_trace_id = contextvars.ContextVar("mxnet_tpu_trace_id", default=None)
_counter = itertools.count()
_salt_lock = threading.Lock()
_salt = None


def _process_salt():
    """Random per-process prefix so ids from different processes (the
    dist_async worker fleet) never collide in a merged event log."""
    global _salt
    if _salt is None:
        with _salt_lock:
            if _salt is None:
                _salt = os.urandom(3).hex()
    return _salt


def new_trace_id(prefix="t"):
    """Mint a process-unique id: ``<prefix><salt>-<pid>-<seq>``."""
    return f"{prefix}{_process_salt()}-{os.getpid():x}-{next(_counter):x}"


def current_trace_id():
    """The active trace id, or None outside any trace context."""
    return _trace_id.get()


def set_trace_id(trace_id):
    """Set the active id; returns a token for ``_trace_id.reset``."""
    return _trace_id.set(trace_id)


def reset_trace_id(token):
    """Undo a :func:`set_trace_id` (spans.py uses the pair to scope a
    minted trace id to one local-root span)."""
    _trace_id.reset(token)


@contextlib.contextmanager
def trace_context(trace_id):
    """``with trace_context(tid):`` — scoped trace id."""
    token = _trace_id.set(trace_id)
    try:
        yield trace_id
    finally:
        _trace_id.reset(token)
