"""Custom operator registration — ``mx.operator`` (reference
python/mxnet/operator.py + src/operator/custom/custom.cc analog).

The reference lets users register a Python operator by name
(``@mx.operator.register("softmax")`` on a ``CustomOpProp`` subclass)
and use it from NDArray, Gluon and Symbol/Module via the ``Custom`` op,
with forward/backward/infer_shape callbacks crossing the C FFI on a
dedicated worker thread. TPU-native redesign: the user's callbacks run
*inside the trace* — forward/backward receive NDArrays that wrap JAX
tracers, so a CustomOp written with ``mx.nd`` ops compiles into the same
XLA computation as everything around it (no host round-trip per call,
which on an accelerator-over-network setup would dominate). The
gradient contract is kept with ``jax.custom_vjp``: autograd/jit call the
user's ``backward`` instead of differentiating through ``forward``.

Consequences of the traced design (vs the reference's host-side
callbacks):
- callbacks must be jit-traceable (no data-dependent Python branching
  on tensor *values*; shapes/dtypes are concrete as usual);
- ``declare_backward_dependency`` is accepted but unused — XLA's DCE
  keeps exactly the residuals the backward needs;
- auxiliary states are not supported (immutable functional arrays);
  ``list_auxiliary_states`` must return ``[]``;
- ``create_operator`` runs once per forward AND once per backward —
  do not stash tensors on ``self`` in ``forward`` expecting them in
  ``backward`` (tracer state cannot cross the jax.custom_vjp boundary
  anyway); everything the backward needs is in its
  ``in_data``/``out_data``/``out_grad`` arguments.

Example (the classic custom softmax loss, reference
example/numpy-ops/custom_softmax.py shape):

    @mx.operator.register("mysoftmax")
    class MySoftmaxProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=False)
        def list_arguments(self):
            return ["data", "label"]
        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []
        def create_operator(self, ctx, shapes, dtypes):
            return MySoftmax()

    out = mx.nd.Custom(data, label, op_type="mysoftmax")
    sym = mx.sym.Custom(data=x, label=y, op_type="mysoftmax")
"""
from __future__ import annotations

import functools

import jax
import numpy as np

from .base import MXNetError
from .context import current_context

__all__ = ["CustomOp", "CustomOpProp", "register",
           "get_all_registered_operators"]

# op_type -> CustomOpProp subclass
_PROP_REGISTRY: dict[str, type] = {}


class CustomOp:
    """Base class for the user's operator implementation (reference
    mxnet.operator.CustomOp). ``forward``/``backward`` receive lists of
    NDArrays; results are written into the provided output lists with
    :meth:`assign`."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """Honor the write request: 'write'/'inplace' overwrite, 'add'
        accumulates, 'null' is a no-op."""
        if req == "null":
            return
        from .ndarray import NDArray
        if not isinstance(src, NDArray):
            from .ndarray.ndarray import _wrap
            src = _wrap(src, dst.ctx)
        if req == "add":
            dst._set_data((dst + src)._data)
        else:  # write / inplace
            dst._set_data(src._data if src.dtype == dst.dtype
                          else src.astype(dst.dtype)._data)


class CustomOpProp:
    """Operator property class: declares the interface of a custom op
    (reference mxnet.operator.CustomOpProp). Subclass and override."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = bool(need_top_grad)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def infer_shape(self, in_shape):
        """Default: all inputs share the first input's shape; one output
        of that shape (reference default)."""
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        """Default: everything takes the first input's dtype."""
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def infer_storage_type(self, in_stype):
        return (in_stype, ["default"] * len(self.list_outputs()),
                ["default"] * len(self.list_auxiliary_states()))

    def infer_storage_type_backward(self, ograd_stype, in_stype, out_stype,
                                    igrad_stype, aux_stype):
        return (ograd_stype, in_stype, out_stype,
                ["default"] * len(in_stype), aux_stype)

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        """Accepted for parity; residual liveness is XLA's job here."""
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def need_top_grad(self):
        return self.need_top_grad_

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Class decorator: register a CustomOpProp subclass under a name
    usable as ``op_type`` (reference mx.operator.register)."""

    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise MXNetError(
                f"mx.operator.register({reg_name!r}) expects a CustomOpProp "
                f"subclass, got {prop_cls}")
        _PROP_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered_operators():
    """Names registered via mx.operator.register."""
    return sorted(_PROP_REGISTRY)


def _make_prop(op_type, params):
    """Instantiate the registered prop with the op's non-tensor params
    (the reference passes every kwarg to the prop ctor as a string)."""
    try:
        prop_cls = _PROP_REGISTRY[op_type]
    except KeyError:
        raise MXNetError(
            f"custom op type {op_type!r} is not registered; known: "
            f"{get_all_registered_operators()}") from None
    kwargs = {k: (v if isinstance(v, str) else str(v))
              for k, v in params.items()}
    return prop_cls(**kwargs)


def _check_no_aux(prop, op_type):
    if prop.list_auxiliary_states():
        raise MXNetError(
            f"custom op {op_type!r}: auxiliary states are not supported in "
            "the traced CustomOp design (functional arrays are immutable); "
            "model state belongs in Gluon Parameters")


def _np_dtype(d):
    return np.dtype(d)


@functools.lru_cache(maxsize=None)
def _build_custom_fn(op_type, frozen_params, n_args, is_train):
    """Build (and cache) the jax.custom_vjp callable for one
    (op_type, params) instantiation. The forward runs the user's
    CustomOp.forward on tracer-backed NDArrays; the custom VJP runs the
    user's backward — so autograd and jit both honor the user's gradient
    (reference: CustomOperator dispatches forward/backward callbacks,
    src/operator/custom/custom.cc)."""
    from . import autograd as _autograd
    from .ndarray.ndarray import _wrap
    from .ndarray import zeros as _nd_zeros

    params = dict(frozen_params)
    prop = _make_prop(op_type, params)
    _check_no_aux(prop, op_type)
    arg_names = list(prop.list_arguments())
    out_names = list(prop.list_outputs())
    if n_args != len(arg_names):
        raise MXNetError(
            f"custom op {op_type!r} declares {len(arg_names)} arguments "
            f"{arg_names} but was called with {n_args} tensor inputs")

    def _shapes_dtypes(arrays):
        in_shapes = [list(a.shape) for a in arrays]
        ret = prop.infer_shape(in_shapes)
        if len(ret) < 2:
            raise MXNetError(
                f"custom op {op_type!r}: infer_shape must return "
                "(in_shape, out_shape, aux_shape)")
        out_shapes = ret[1]
        tret = prop.infer_type([_np_dtype(a.dtype) for a in arrays])
        out_dtypes = tret[1]
        return out_shapes, out_dtypes

    def _run_forward(arrays, train):
        ctx = current_context()
        out_shapes, out_dtypes = _shapes_dtypes(arrays)
        in_data = [_wrap(a, ctx) for a in arrays]
        out_data = [_nd_zeros(tuple(int(d) for d in s), ctx=ctx,
                              dtype=_np_dtype(t))
                    for s, t in zip(out_shapes, out_dtypes)]
        op_inst = prop.create_operator(ctx, [list(a.shape) for a in arrays],
                                       [_np_dtype(a.dtype) for a in arrays])
        prev = _autograd.set_recording(False)
        try:
            op_inst.forward(is_train=train, req=["write"] * len(out_data),
                            in_data=in_data, out_data=out_data, aux=[])
        finally:
            _autograd.set_recording(prev)
        return tuple(o._data for o in out_data), op_inst

    @jax.custom_vjp
    def custom_call(*arrays):
        outs, _ = _run_forward(arrays, is_train)
        return outs

    def fwd(*arrays):
        outs, _ = _run_forward(arrays, True)
        return outs, (arrays, outs)

    def bwd(res, cotangents):
        arrays, outs = res
        ctx = current_context()
        in_data = [_wrap(a, ctx) for a in arrays]
        out_data = [_wrap(o, ctx) for o in outs]
        out_grad = [_wrap(c, ctx) for c in cotangents]
        in_grad = [_wrap(jax.numpy.zeros(a.shape, a.dtype), ctx)
                   for a in arrays]
        op_inst = prop.create_operator(ctx, [list(a.shape) for a in arrays],
                                       [_np_dtype(a.dtype) for a in arrays])
        prev = _autograd.set_recording(False)
        try:
            op_inst.backward(req=["write"] * len(in_grad),
                             out_grad=out_grad, in_data=in_data,
                             out_data=out_data, in_grad=in_grad, aux=[])
        finally:
            _autograd.set_recording(prev)
        grads = []
        for a, g in zip(arrays, in_grad):
            if np.issubdtype(np.dtype(a.dtype), np.floating):
                grads.append(g._data.astype(a.dtype))
            else:
                # integer/bool primals take float0 cotangents
                grads.append(np.zeros(a.shape, jax.dtypes.float0))
        return tuple(grads)

    custom_call.defvjp(fwd, bwd)
    return custom_call, len(out_names)


def _invoke_custom(*arrays, op_type=None, **params):
    """Registry impl of the ``Custom`` op (pure-JAX callable)."""
    if op_type is None:
        raise MXNetError("Custom requires op_type=")
    from . import autograd as _autograd
    # tensors passed as kwargs would bypass differentiation (jax.vjp
    # wraps positionals only) — reject loudly rather than silently
    # dropping their gradients
    bad = [k for k, v in params.items()
           if hasattr(v, "shape") and hasattr(v, "dtype")]
    if bad:
        raise MXNetError(
            f"Custom: pass tensor inputs positionally (got tensor kwargs "
            f"{bad}); mx.sym.Custom accepts named tensor kwargs")
    fn, _ = _build_custom_fn(op_type, tuple(sorted(params.items())),
                             len(arrays), _autograd.is_training())
    out = fn(*arrays)
    return out if len(out) > 1 else out[0]


def _custom_num_outputs(params):
    """Symbol-arity hook: output count from the prop's list_outputs()."""
    p = dict(params)
    p.pop("name", None)
    op_type = p.pop("op_type", None)
    if op_type is None:
        return 1
    return len(_make_prop(op_type, p).list_outputs())


def _custom_input_names(params):
    """Symbol input-name hook: the prop's list_arguments()."""
    p = dict(params)
    p.pop("name", None)
    op_type = p.pop("op_type", None)
    if op_type is None:
        return None
    return list(_make_prop(op_type, p).list_arguments())


def _register_custom_op():
    from .ndarray.register import register_op

    register_op("Custom", differentiable=True,
                infer_num_outputs=_custom_num_outputs,
                infer_input_names=_custom_input_names)(_invoke_custom)


_register_custom_op()
