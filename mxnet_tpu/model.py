"""Model checkpoint helpers (python/mxnet/model.py analog):
save_checkpoint/load_checkpoint (symbol JSON + .params pairs), plus the
BatchEndParam namedtuple every callback consumes."""
from __future__ import annotations

from collections import namedtuple

from .base import MXNetError

__all__ = ["BatchEndParam", "save_checkpoint", "load_checkpoint", "load_params"]

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params,
                    remove_amp_cast=True):
    """Save 'prefix-symbol.json' + 'prefix-%04d.params' (reference format:
    arg:/aux: prefixed names in one NDArray file)."""
    from . import ndarray as nd
    if symbol is not None:
        symbol.save(f"{prefix}-symbol.json")
    save_dict = {f"arg:{k}": v for k, v in arg_params.items()}
    save_dict.update({f"aux:{k}": v for k, v in (aux_params or {}).items()})
    nd.save(f"{prefix}-{epoch:04d}.params", save_dict)


def load_params(prefix, epoch):
    from . import ndarray as nd
    save_dict = nd.load(f"{prefix}-{epoch:04d}.params")
    arg_params, aux_params = {}, {}
    if isinstance(save_dict, list):
        raise MXNetError("params file has no names; cannot split arg/aux")
    for k, v in save_dict.items():
        tp, _, name = k.partition(":")
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
        else:
            arg_params[k] = v
    return arg_params, aux_params


def load_checkpoint(prefix, epoch):
    from . import symbol as sym
    symbol = sym.load(f"{prefix}-symbol.json")
    arg_params, aux_params = load_params(prefix, epoch)
    return symbol, arg_params, aux_params
