"""Base utilities: dtypes, errors, registries.

TPU-native analog of the reference's FFI/base layer
(``python/mxnet/base.py`` in apache/mxnet v1.x). There is no C ABI here:
the "backend" is JAX/XLA, so this module only carries the shared dtype
tables, error types, and the generic registry that powers op-namespace
codegen (the ``_init_op_module`` analog).
"""
from __future__ import annotations

import numpy as np

try:  # jax is the required backend
    import jax
    # MXNet exposes float64/int64 tensors natively (int64 indexing is a
    # nightly test tier in the reference); enable x64 so dtypes round-trip.
    # Python-float inputs still default to float32 in mx.nd.array.
    jax.config.update("jax_enable_x64", True)
    import jax.numpy as jnp
except ImportError as e:  # pragma: no cover
    raise ImportError("mxnet_tpu requires jax") from e

__all__ = [
    "MXNetError",
    "string_types",
    "numeric_types",
    "integer_types",
    "DTYPE_NAME_TO_NP",
    "NP_TO_DTYPE_NAME",
    "dtype_np",
    "dtype_name",
]


class MXNetError(RuntimeError):
    """Framework error type (analog of ``mxnet.base.MXNetError``)."""


string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)

# dtype tables — mirrors the reference's mshadow type enum surface
# (int dtype codes from include/mxnet/base.h / mshadow), extended with
# bfloat16 which is the TPU-native half type.
DTYPE_NAME_TO_NP = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "bfloat16": jnp.bfloat16,
    "uint8": np.uint8,
    "int8": np.int8,
    "int16": np.int16,
    "int32": np.int32,
    "int64": np.int64,
    "bool": np.bool_,
}

NP_TO_DTYPE_NAME = {np.dtype(v): k for k, v in DTYPE_NAME_TO_NP.items() if k != "bfloat16"}
NP_TO_DTYPE_NAME[jnp.dtype(jnp.bfloat16)] = "bfloat16"

# Legacy integer dtype codes (reference: mshadow/base.h kFloat32=0 etc.)
# kept so serialized .params files / user code using int codes round-trip.
DTYPE_CODE_TO_NAME = {
    0: "float32",
    1: "float64",
    2: "float16",
    3: "uint8",
    4: "int32",
    5: "int8",
    6: "int64",
    7: "bool",
    12: "bfloat16",
}
DTYPE_NAME_TO_CODE = {v: k for k, v in DTYPE_CODE_TO_NAME.items()}


def dtype_np(dtype):
    """Normalize a user-provided dtype (str | np.dtype | type | int code) to a dtype object."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, int):
        dtype = DTYPE_CODE_TO_NAME[dtype]
    if isinstance(dtype, str):
        if dtype == "bfloat16":
            return jnp.dtype(jnp.bfloat16)
        return np.dtype(DTYPE_NAME_TO_NP[dtype])
    return jnp.dtype(dtype)


def dtype_name(dtype) -> str:
    """Canonical string name of a dtype."""
    d = jnp.dtype(dtype)
    if d == jnp.dtype(jnp.bfloat16):
        return "bfloat16"
    return NP_TO_DTYPE_NAME.get(np.dtype(d.name), d.name)


class _Registry:
    """Name → object registry with alias support.

    Analog of ``dmlc::Registry`` (reference: 3rdparty/dmlc-core
    include/dmlc/registry.h), which the reference uses for ops,
    optimizers, iterators and initializers alike.
    """

    def __init__(self, kind: str):
        self.kind = kind
        self._map: dict[str, object] = {}

    def register(self, name=None, *aliases):
        def _do(obj, nm):
            key = (nm or getattr(obj, "__name__", None)).lower()
            self._map[key] = obj
            for a in aliases:
                self._map[a.lower()] = obj
            return obj

        if callable(name):  # used as bare decorator
            return _do(name, None)
        return lambda obj: _do(obj, name)

    def get(self, name: str):
        key = name.lower()
        if key not in self._map:
            raise MXNetError(
                f"{self.kind} '{name}' is not registered. "
                f"Known: {sorted(self._map)}"
            )
        return self._map[key]

    def find(self, name: str):
        return self._map.get(name.lower())

    def list(self):
        return sorted(self._map)
