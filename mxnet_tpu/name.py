"""NameManager — automatic symbol/block naming (python/mxnet/name.py)."""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class NameManager:
    _current = threading.local()

    def __init__(self):
        self._counter = {}
        self._old = None

    def get(self, name, hint):
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = f"{hint}{self._counter[hint]}"
        self._counter[hint] += 1
        return name

    def __enter__(self):
        self._old = getattr(NameManager._current, "value", None)
        NameManager._current.value = self
        return self

    def __exit__(self, *exc):
        NameManager._current.value = self._old
        return False

    @staticmethod
    def current() -> "NameManager":
        cur = getattr(NameManager._current, "value", None)
        if cur is None:
            cur = NameManager()
            NameManager._current.value = cur
        return cur


class Prefix(NameManager):
    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        return name if name else self._prefix + super().get(name, hint)
