"""Named-axis collectives.

The XLA-collective replacements for the reference's communication
backends (SURVEY §5.8): ncclAllReduce → lax.psum over a mesh axis;
CommDevice ring/tree reduce → the partitioner's AllReduce; ps-lite
ZPush/ZPull → psum over the DCN-spanning axis; CUDA P2P CopyFromTo →
lax.ppermute. Use inside shard_map/jit; these are thin wrappers that
keep MXNet-ish naming.
"""
from __future__ import annotations

import jax
from jax import lax

__all__ = ["allreduce", "allgather", "reduce_scatter", "ppermute",
           "alltoall", "axis_index", "axis_size"]


def allreduce(x, axis_name: str, op: str = "sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError(f"unknown allreduce op {op}")


def allgather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, axis: int = 0):
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute(x, axis_name: str, perm):
    return lax.ppermute(x, axis_name, perm)


def alltoall(x, axis_name: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str):
    return lax.axis_size(axis_name) if hasattr(lax, "axis_size") else lax.psum(1, axis_name)
