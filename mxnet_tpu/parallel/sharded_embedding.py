"""Sharded embedding tables with all-to-all exchange (expert/embedding
parallelism over ICI).

SURVEY §2.4 names "sharded embedding tables + all-to-all over ICI" as
the TPU-native equivalent of the reference's row_sparse embedding +
kvstore sparse pull/push (src/kvstore/kvstore_dist.h sparse path,
gluon/contrib SparseEmbedding): instead of every worker pulling rows
from a parameter server, the table lives row-sharded across the mesh
and lookups route to the owning shard with ``lax.all_to_all`` — the
DLRM-style exchange, bandwidth-optimal on the torus.

Protocol per device (inside shard_map, axis ``ep``, n devices):
1. bucket the local batch's ids by owner shard (sort + fixed capacity
   c = local batch size — worst case every id lives on one shard);
2. ``all_to_all`` the (n, c) id buckets → each shard receives the ids
   it owns;
3. local gather from the table shard → (n, c, E) rows;
4. ``all_to_all`` back → senders reassemble their batch's embeddings.

Everything is static-shape (pad slots route row 0 and are zeroed on
return), so the whole exchange jits into one XLA program; the backward
transposes the all_to_alls and scatter-adds into the owning shard —
the gradient never materializes the full table anywhere.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["make_sharded_embedding_fn", "shard_embedding_table"]


def shard_embedding_table(table, mesh, axis_name="ep"):
    """Place a (V, E) table row-sharded over ``axis_name``. V must be
    divisible by the axis size."""
    n = mesh.shape[axis_name]
    if table.shape[0] % n:
        raise ValueError(
            f"table rows {table.shape[0]} not divisible by mesh axis "
            f"{axis_name}={n}")
    return jax.device_put(table, NamedSharding(mesh, P(axis_name, None)))


def _local_lookup(table_l, ids_l, axis_name, n=None):
    """Per-device body: bucketed all_to_all exchange (see module doc).
    ``n`` is the static axis size — callers pass mesh.shape[axis_name]
    (lax.axis_size only exists on newer jax, and the size must be a
    python int for the arange/bucket shapes anyway)."""
    if n is None:
        n = lax.axis_size(axis_name)
    rows = table_l.shape[0]
    b = ids_l.shape[0]
    c = b  # bucket capacity: worst case all local ids on one shard

    owner = (ids_l // rows).astype(jnp.int32)
    order = jnp.argsort(owner)  # stable: groups ids by destination
    sorted_ids = ids_l[order]
    cnt = jnp.sum(owner[None, :] == jnp.arange(n)[:, None], axis=1)  # (n,)
    start = jnp.cumsum(cnt) - cnt
    k_idx = start[:, None] + jnp.arange(c)[None, :]          # (n, c)
    valid = jnp.arange(c)[None, :] < cnt[:, None]            # (n, c)
    gather_idx = jnp.clip(k_idx, 0, b - 1)
    send_ids = jnp.where(valid, sorted_ids[gather_idx], 0)   # (n, c)

    # row i of send_ids goes to device i; receive one row from each
    recv_ids = lax.all_to_all(send_ids, axis_name, 0, 0)
    me = lax.axis_index(axis_name)
    local = jnp.clip(recv_ids - me * rows, 0, rows - 1)
    vals = table_l[local]                                    # (n, c, E)
    back = lax.all_to_all(vals, axis_name, 0, 0)             # (n, c, E)

    contrib = jnp.where(valid[..., None], back, 0.0)
    out = jnp.zeros((b, table_l.shape[1]), table_l.dtype)
    out = out.at[order[gather_idx].reshape(-1)].add(
        contrib.reshape(-1, table_l.shape[1]).astype(table_l.dtype))
    return out


def make_sharded_embedding_fn(mesh, axis_name="ep", batch_axis=None):
    """Build ``lookup(table, ids) -> (batch, E)`` where the table is
    row-sharded over ``axis_name`` and the batch is sharded over
    ``batch_axis`` (defaults to ``axis_name`` — the pure-EP layout).

    Passing a distinct ``batch_axis`` composes EP with data
    parallelism on one mesh: ids shard over (batch_axis, axis_name)
    jointly — every device owns a distinct slice of the batch — and
    the all_to_all exchange rides the table axis within each dp row
    (the DLRM dp x ep layout; splitting the dp-shard across tp peers
    also divides the exchange work instead of duplicating it).

    Differentiable: grad w.r.t. the table stays sharded (scatter-add on
    the owning shard via the transposed exchange). ids length must be
    divisible by the product of the named axis sizes.
    """
    id_spec = (P((batch_axis, axis_name)) if batch_axis
               and batch_axis != axis_name else P(axis_name))

    n = int(mesh.shape[axis_name])

    def lookup(table, ids):
        return shard_map(
            lambda t, i: _local_lookup(t, i.reshape(-1), axis_name, n),
            mesh=mesh,
            in_specs=(P(axis_name, None), id_spec),
            out_specs=id_spec,
        )(table, ids)

    return lookup
