"""Device-mesh construction.

Replaces the reference's device-topology machinery
(src/kvstore/gpu_topology.h PCIe/NVLink tree planning, comm.h device
lists): on TPU the fabric is the ICI torus and XLA's partitioner plans
the routes, so "topology planning" reduces to choosing mesh axis sizes.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = ["build_mesh", "local_mesh", "data_parallel_mesh",
           "current_mesh", "set_current_mesh"]

_CURRENT: Optional[Mesh] = None


def build_mesh(axis_shapes: dict[str, int], devices=None) -> Mesh:
    """Build a Mesh from {axis_name: size}. Use -1 for one axis to absorb
    the remaining devices (like a reshape).

    Example: build_mesh({"dp": -1, "tp": 4}) on 32 chips → 8×4 mesh.
    """
    devices = list(devices if devices is not None else jax.devices())
    names = list(axis_shapes.keys())
    sizes = list(axis_shapes.values())
    n = len(devices)
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = max(1, n // known)
    total = math.prod(sizes)
    if total > n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}")
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def local_mesh(axis_name: str = "dp", devices=None) -> Mesh:
    """1-D mesh over this process's addressable devices."""
    devices = list(devices if devices is not None else jax.local_devices())
    return Mesh(np.asarray(devices), (axis_name,))


def data_parallel_mesh(devices=None) -> Mesh:
    """1-D global mesh over all devices — the KVStore-allreduce analog
    (data axis rides ICI within a slice, DCN across slices)."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.asarray(devices), ("dp",))


def current_mesh() -> Optional[Mesh]:
    return _CURRENT


def set_current_mesh(mesh: Optional[Mesh]):
    global _CURRENT
    _CURRENT = mesh
    return mesh
