"""Jitted bucketed gradient allreduce — the CommDevice/NCCL analog.

Reference behavior being replaced (SURVEY §2.1/§3.2): KVStore 'device'
reduces per-GPU gradients with a P2P add tree (src/kvstore/comm.h
CommDevice) and 'nccl' with ncclAllReduce (kvstore_nccl.h), both fusing
many small tensors into buckets. TPU-first redesign: the per-context
gradient replicas of one logical parameter already live on distinct
chips, so we view them as ONE global array whose leading "replica" axis
is sharded over a 1-D device mesh, and compile `sum(axis=0)` with a
replicated output sharding. The XLA SPMD partitioner turns that into an
ICI/DCN AllReduce, and its all-reduce combiner pass fuses the reduces
of every parameter in the bucket — the NCCL-bucketing analog, but done
by the compiler.

One AOT-compiled executable is cached per (device tuple, shapes/dtypes)
structure — the whole parameter set is one bucket, so Trainer.step
dispatches ONE compiled computation per step regardless of param count.
Multi-process (DistKVStore) uses the same mechanism over the global
device list: every process contributes its local shards and executes
the same SPMD program, which is exactly jax multihost jit semantics.
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["reduce_replica_lists", "can_fast_reduce", "last_hlo_text"]

# (devices, shapes/dtypes) -> (executable, stack_sharding, hlo text)
_CACHE: dict = {}
_LAST_HLO: list = [None]


def last_hlo_text():
    """HLO of the most recently used reduce executable (test hook: the
    multi-device tests assert an all-reduce is in the compiled text)."""
    return _LAST_HLO[0]


def can_fast_reduce(value_lists: Sequence[Sequence]) -> bool:
    """True when every key's per-context arrays sit on the same tuple of
    distinct devices (the Trainer layout) — the jitted stacked-psum path
    applies. Single-element lists are fine (pure multi-process reduce).
    """
    if not value_lists:
        return False
    dev0 = None
    for vlist in value_lists:
        devs = tuple(v.device for v in vlist)
        if len(set(devs)) != len(devs):
            return False
        if dev0 is None:
            dev0 = devs
        elif devs != dev0:
            return False
    return True


def _build(devices, shapes_dtypes):
    mesh = Mesh(np.asarray(devices), ("dp",))
    stack_sh = NamedSharding(mesh, P("dp"))
    repl_sh = NamedSharding(mesh, P())

    def reduce_all(stacked):
        return [x.sum(axis=0) for x in stacked]

    avals = [jax.ShapeDtypeStruct((len(devices),) + tuple(s), d,
                                  sharding=stack_sh)
             for s, d in shapes_dtypes]
    lowered = jax.jit(
        reduce_all, out_shardings=[repl_sh] * len(shapes_dtypes)).lower(avals)
    compiled = lowered.compile()
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = ""
    return compiled, stack_sh, hlo


def reduce_replica_lists(value_lists, devices=None):
    """Sum each key's per-device replica arrays in ONE compiled call.

    value_lists: list (over keys) of lists of same-shape jax.Arrays,
    each inner list holding one array per device of ``devices`` (order
    irrelevant — arrays are matched to mesh positions by .device).
    devices: the participating device tuple; defaults to the devices of
    the first list (single-process). For multi-process reduce pass the
    GLOBAL device list — local arrays are the addressable shards.

    Returns a list of globally-replicated jax.Arrays (one per key);
    read per-device copies off ``.addressable_shards``.
    """
    if devices is None:
        devices = tuple(a.device for a in value_lists[0])
    devices = tuple(devices)
    n = len(devices)
    shapes_dtypes = tuple(
        (tuple(v[0].shape), jnp.dtype(v[0].dtype)) for v in value_lists)
    key = (devices, shapes_dtypes)
    entry = _CACHE.get(key)
    if entry is None:
        entry = _build(devices, shapes_dtypes)
        _CACHE[key] = entry
    compiled, stack_sh, hlo = entry
    _LAST_HLO[0] = hlo

    stacked = []
    for vlist, (shape, dtype) in zip(value_lists, shapes_dtypes):
        # device_put commits an (possibly uncommitted) array to its own
        # device so the reshape below cannot migrate it to the default
        # device (no copy is made for an already-resident buffer).
        shards = [jax.device_put(v, v.device).reshape((1,) + shape)
                  for v in vlist]
        stacked.append(jax.make_array_from_single_device_arrays(
            (n,) + shape, stack_sh, shards))
    return compiled(stacked)


def reduce_compressed_replica_lists(value_lists, residual_lists,
                                    devices=None, ctype="2bit",
                                    threshold=0.5):
    """Gradient-compressed fused reduce with error feedback — the
    reference GradientCompression (src/kvstore/gradient_compression.cc)
    redesigned for compiled collectives: quantization, residual update
    and the all-reduce are ONE XLA computation; residuals stay sharded
    per device, the reduced value comes back replicated.

    ctype '2bit': each element of (grad + residual) maps to
    {+threshold, 0, -threshold}; residual accumulates the error
    (reference 2-bit stochastic quantization contract). ctype 'int8':
    symmetric per-tensor int8 with the scale computed in-graph.

    Returns (reduced_list, new_residual_lists)."""
    if devices is None:
        devices = tuple(a.device for a in value_lists[0])
    devices = tuple(devices)
    n = len(devices)
    shapes_dtypes = tuple(
        (tuple(v[0].shape), jnp.dtype(v[0].dtype)) for v in value_lists)
    key = ("compressed", devices, shapes_dtypes, ctype, float(threshold))
    entry = _CACHE.get(key)
    if entry is None:
        mesh = Mesh(np.asarray(devices), ("dp",))
        stack_sh = NamedSharding(mesh, P("dp"))
        repl_sh = NamedSharding(mesh, P())
        t = float(threshold)

        def reduce_all(stacked_g, stacked_r):
            outs, new_rs = [], []
            for g, r in zip(stacked_g, stacked_r):
                eff = g.astype(jnp.float32) + r
                if ctype == "2bit":
                    q = jnp.where(eff >= t, t,
                                  jnp.where(eff <= -t, -t, 0.0))
                else:  # int8: in-graph symmetric scale per shard
                    amax = jnp.maximum(jnp.max(jnp.abs(eff)), 1e-8)
                    s = amax / 127.0
                    q = jnp.round(eff / s).astype(jnp.int8).astype(jnp.float32) * s
                new_rs.append(eff - q)
                outs.append(q.sum(axis=0).astype(g.dtype))
            return outs, new_rs

        n_keys = len(shapes_dtypes)
        avals_g = [jax.ShapeDtypeStruct((n,) + tuple(s), d, sharding=stack_sh)
                   for s, d in shapes_dtypes]
        avals_r = [jax.ShapeDtypeStruct((n,) + tuple(s), jnp.float32,
                                        sharding=stack_sh)
                   for s, _ in shapes_dtypes]
        compiled = jax.jit(
            reduce_all,
            out_shardings=([repl_sh] * n_keys, [stack_sh] * n_keys),
            donate_argnums=(1,),
        ).lower(avals_g, avals_r).compile()
        try:
            hlo = compiled.as_text()
        except Exception:
            hlo = ""
        entry = (compiled, stack_sh, hlo)
        _CACHE[key] = entry
    compiled, stack_sh, hlo = entry
    _LAST_HLO[0] = hlo

    def stack(vlists):
        out = []
        for vlist, (shape, _) in zip(vlists, shapes_dtypes):
            shards = [jax.device_put(v, v.device).reshape((1,) + shape)
                      for v in vlist]
            out.append(jax.make_array_from_single_device_arrays(
                (n,) + shape, stack_sh, shards))
        return out

    if residual_lists is None:
        # first call: zero error-feedback buffers, sharded like the grads
        residual_lists = [
            jax.make_array_from_callback(
                (n,) + tuple(shape), stack_sh,
                lambda idx, shape=shape: np.zeros(
                    (1,) + tuple(shape), np.float32))
            for shape, _ in shapes_dtypes]
    reduced, new_res = compiled(stack(value_lists), residual_lists)
    # new_res are stacked sharded arrays — hand them back in on the next
    # call (the per-device error-feedback state lives on its device)
    return reduced, new_res


def reduce_grad_ndarrays_inplace(grads):
    """Sum each key's per-context NDArray gradients and write the sum
    back into every replica — the kvstore-less multi-device reduce used
    by Trainer/Module when no store was configured (reference
    executor_group still sums; silently training on divergent replicas
    is never correct). One compiled all-reduce when the replicas sit on
    distinct devices, an eager add-tree otherwise (tests sharing one
    device)."""
    vlists = [[g._data for g in glist] for glist in grads]
    if (can_fast_reduce(vlists) and len(vlists[0]) > 1
            and len({a.device for a in vlists[0]}) == len(vlists[0])):
        reduced = reduce_replica_lists(vlists)
        for glist, garr in zip(grads, reduced):
            for g in glist:
                g._set_data(shard_for_device(garr, g._data.device))
        return
    for glist in grads:
        total = glist[0]
        for g in glist[1:]:
            total = total + g.as_in_context(total.ctx)
        for g in glist:
            g._set_data(total._data if g.ctx == total.ctx
                        else total.as_in_context(g.ctx)._data)


def shard_for_device(garr, device):
    """The addressable shard of a replicated global array on ``device``
    (zero-copy view — this is how reduced gradients get written back
    into each context's NDArray)."""
    for s in garr.addressable_shards:
        if s.data.device == device:
            return s.data
    raise ValueError(f"device {device} not addressable in {garr.sharding}")
