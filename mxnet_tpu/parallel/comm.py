"""Jitted bucketed gradient allreduce — the CommDevice/NCCL analog.

Reference behavior being replaced (SURVEY §2.1/§3.2): KVStore 'device'
reduces per-GPU gradients with a P2P add tree (src/kvstore/comm.h
CommDevice) and 'nccl' with ncclAllReduce (kvstore_nccl.h), both fusing
many small tensors into buckets. TPU-first redesign: the per-context
gradient replicas of one logical parameter already live on distinct
chips, so we view them as ONE global array whose leading "replica" axis
is sharded over a 1-D device mesh, and compile `sum(axis=0)` with a
replicated output sharding. The XLA SPMD partitioner turns that into an
ICI/DCN AllReduce, and its all-reduce combiner pass fuses the reduces
of every parameter in the bucket — the NCCL-bucketing analog, but done
by the compiler.

One AOT-compiled executable is cached per (device tuple, shapes/dtypes)
structure — the whole parameter set is one bucket, so Trainer.step
dispatches ONE compiled computation per step regardless of param count.
Multi-process (DistKVStore) uses the same mechanism over the global
device list: every process contributes its local shards and executes
the same SPMD program, which is exactly jax multihost jit semantics.
"""
from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["reduce_replica_lists", "can_fast_reduce", "last_hlo_text"]

# (devices, shapes/dtypes) -> (executable, stack_sharding, hlo text)
_CACHE: dict = {}
_LAST_HLO: list = [None]


def last_hlo_text():
    """HLO of the most recently used reduce executable (test hook: the
    multi-device tests assert an all-reduce is in the compiled text)."""
    return _LAST_HLO[0]


def can_fast_reduce(value_lists: Sequence[Sequence]) -> bool:
    """True when every key's per-context arrays sit on the same tuple of
    distinct devices (the Trainer layout) — the jitted stacked-psum path
    applies. Single-element lists are fine (pure multi-process reduce).
    """
    if not value_lists:
        return False
    dev0 = None
    for vlist in value_lists:
        devs = tuple(v.device for v in vlist)
        if len(set(devs)) != len(devs):
            return False
        if dev0 is None:
            dev0 = devs
        elif devs != dev0:
            return False
    return True


def _build(devices, shapes_dtypes):
    mesh = Mesh(np.asarray(devices), ("dp",))
    stack_sh = NamedSharding(mesh, P("dp"))
    repl_sh = NamedSharding(mesh, P())

    def reduce_all(stacked):
        return [x.sum(axis=0) for x in stacked]

    avals = [jax.ShapeDtypeStruct((len(devices),) + tuple(s), d,
                                  sharding=stack_sh)
             for s, d in shapes_dtypes]
    lowered = jax.jit(
        reduce_all, out_shardings=[repl_sh] * len(shapes_dtypes)).lower(avals)
    compiled = lowered.compile()
    try:
        hlo = compiled.as_text()
    except Exception:
        hlo = ""
    return compiled, stack_sh, hlo


def reduce_replica_lists(value_lists, devices=None):
    """Sum each key's per-device replica arrays in ONE compiled call.

    value_lists: list (over keys) of lists of same-shape jax.Arrays,
    each inner list holding one array per device of ``devices`` (order
    irrelevant — arrays are matched to mesh positions by .device).
    devices: the participating device tuple; defaults to the devices of
    the first list (single-process). For multi-process reduce pass the
    GLOBAL device list — local arrays are the addressable shards.

    Returns a list of globally-replicated jax.Arrays (one per key);
    read per-device copies off ``.addressable_shards``.
    """
    if devices is None:
        devices = tuple(a.device for a in value_lists[0])
    devices = tuple(devices)
    n = len(devices)
    shapes_dtypes = tuple(
        (tuple(v[0].shape), jnp.dtype(v[0].dtype)) for v in value_lists)
    key = (devices, shapes_dtypes)
    entry = _CACHE.get(key)
    if entry is None:
        entry = _build(devices, shapes_dtypes)
        _CACHE[key] = entry
    compiled, stack_sh, hlo = entry
    _LAST_HLO[0] = hlo

    stacked = []
    for vlist, (shape, dtype) in zip(value_lists, shapes_dtypes):
        # device_put commits an (possibly uncommitted) array to its own
        # device so the reshape below cannot migrate it to the default
        # device (no copy is made for an already-resident buffer).
        shards = [jax.device_put(v, v.device).reshape((1,) + shape)
                  for v in vlist]
        stacked.append(jax.make_array_from_single_device_arrays(
            (n,) + shape, stack_sh, shards))
    return compiled(stacked)


def shard_for_device(garr, device):
    """The addressable shard of a replicated global array on ``device``
    (zero-copy view — this is how reduced gradients get written back
    into each context's NDArray)."""
    for s in garr.addressable_shards:
        if s.data.device == device:
            return s.data
    raise ValueError(f"device {device} not addressable in {garr.sharding}")
