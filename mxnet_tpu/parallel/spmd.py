"""Sharded train-step builders (the pjit/GSPMD path).

This is the performance core that replaces the reference's
Trainer→KVStore→NCCL pipeline (SURVEY §3.2) with one jitted SPMD
computation: forward + backward + optimizer update compiled together,
parameters/optimizer state living sharded on the mesh, gradients
reduced by the XLA partitioner over ICI. ``make_sharded_train_step``
additionally supports tensor-parallel sharding rules — surface the
reference never had (SURVEY §2.4: model parallel was manual
`group2ctx`); here it's a sharding annotation.
"""
from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["shard_params", "replicate", "make_data_parallel_step",
           "make_sharded_train_step", "zero1_spec", "make_zero1_train_step"]


def replicate(tree, mesh: Mesh):
    """Place every leaf replicated over the mesh."""
    s = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, s), tree)


def shard_params(params: dict, mesh: Mesh, rules=None):
    """Place parameters on the mesh.

    rules: list of (name_predicate, PartitionSpec). First match wins;
    default is replication. Example TP rule set for a transformer:
        [(lambda n: n.endswith("ffn_in.weight"), P("tp", None)),
         (lambda n: n.endswith("ffn_out.weight"), P(None, "tp"))]
    """
    out = {}
    for name, arr in params.items():
        spec = P()
        for pred, s in (rules or []):
            if pred(name):
                spec = s
                break
        out[name] = jax.device_put(arr, NamedSharding(mesh, spec))
    return out


def _make_step(loss_fn, optimizer_update, chain):
    """Validate ``chain`` and pick the single or chained step body (the
    one place chain policy lives)."""
    if chain < 1:
        raise ValueError(f"chain must be >= 1, got {chain}")
    if chain > 1:
        return _chained_step(loss_fn, optimizer_update, chain)
    return _single_step(loss_fn, optimizer_update)


def _single_step(loss_fn, optimizer_update):
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        new_params, new_opt = optimizer_update(params, grads, opt_state)
        return new_params, new_opt, loss

    return step


def _chained_step(loss_fn, optimizer_update, chain):
    """One dispatched executable running ``chain`` REAL train steps:
    the batch carries a leading micro-batch axis of size ``chain`` and
    lax.scan consumes one slice per step (engine.chain_steps semantics,
    but with distinct data per sub-step — the host enqueues once per
    ``chain`` optimizer updates, hiding per-dispatch latency the way
    the reference's threaded engine pipelines ahead). Returns
    (params, opt_state, losses (chain,))."""

    def step(params, opt_state, batch):
        leading = {leaf.shape[0] for leaf in jax.tree_util.tree_leaves(batch)}
        if leading != {chain}:
            raise ValueError(
                f"chain={chain} expects every batch leaf to carry a "
                f"leading stacked-micro-batch axis of that size; got "
                f"leading dims {sorted(leading)}")

        def body(carry, b):
            p, o = carry
            loss, grads = jax.value_and_grad(loss_fn)(p, b)
            p, o = optimizer_update(p, grads, o)
            return (p, o), loss

        (p, o), losses = jax.lax.scan(body, (params, opt_state), batch,
                                      length=chain)
        return p, o, losses

    return step


def make_data_parallel_step(loss_fn: Callable, optimizer_update: Callable,
                            mesh: Mesh, data_axis: str = "dp",
                            donate: bool = True, chain: int = 1):
    """Build jit(train_step) where the batch is sharded over `data_axis`
    and parameters are replicated — classic DP, gradients allreduced by
    the partitioner (the KVStore-pushpull analog, compiled away).

    loss_fn(params, batch) -> scalar loss
    optimizer_update(params, grads, opt_state) -> (params, opt_state)

    ``chain > 1``: each call runs that many REAL steps in one dispatch;
    every batch leaf gains a LEADING axis of size ``chain`` (stacked
    micro-batches), the returned loss becomes a (chain,) vector, and
    per-dispatch host latency amortizes across the whole chain.
    """
    step = _make_step(loss_fn, optimizer_update, chain)
    repl = NamedSharding(mesh, P())
    bspec = P(None, data_axis) if chain > 1 else P(data_axis)
    batch_sharding = NamedSharding(mesh, bspec)

    jitted = jax.jit(
        step,
        in_shardings=(repl, repl, batch_sharding),
        out_shardings=(repl, repl, repl),
        donate_argnums=(0, 1) if donate else (),
    )
    return jitted


def make_sharded_train_step(loss_fn: Callable, optimizer_update: Callable,
                            mesh: Mesh,
                            param_spec_fn: Optional[Callable] = None,
                            batch_spec=None, opt_spec_fn=None,
                            donate: bool = True, chain: int = 1):
    """Fully general SPMD train step: parameters sharded per
    `param_spec_fn(path, aval) -> PartitionSpec` (tp/ep-style),
    batch sharded per `batch_spec` (dp/sp), optimizer state sharded per
    `opt_spec_fn` (ZeRO-style — see :func:`zero1_spec`). XLA inserts
    all collectives: with a ZeRO opt spec the partitioner turns the
    gradient all-reduce into reduce-scatter (each dp shard updates its
    slice of the moments) + all-gather of the updated params — the
    ZeRO-1 dataflow, derived from sharding annotations rather than
    hand-written like the reference's DCASGD/ps-lite update paths.
    ``chain > 1`` runs that many real steps per dispatch over a leading
    stacked-micro-batch axis (see make_data_parallel_step).
    """
    def spec_of(tree, fn):
        flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
        specs = [fn(jax.tree_util.keystr(path), leaf) for path, leaf in flat]
        return jax.tree_util.tree_unflatten(treedef, specs)

    step = _make_step(loss_fn, optimizer_update, chain)

    def compile_for(params, opt_state, batch):
        pfn = param_spec_fn or (lambda path, aval: P())
        pspec = spec_of(params, pfn)
        to_sharding = lambda spec: jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), spec,
            is_leaf=lambda s: isinstance(s, P))
        p_sh = to_sharding(pspec)
        ofn = opt_spec_fn or (lambda path, aval: P())
        o_sh = to_sharding(spec_of(opt_state, ofn))
        bs = batch_spec if batch_spec is not None else P()
        if chain > 1:
            # leading axis is the chain (scan) axis — never sharded;
            # shift the caller's per-micro-batch spec right by one
            bs = P(None, *bs)
        b_sh = jax.tree_util.tree_map(
            lambda _: NamedSharding(mesh, bs), batch)
        return jax.jit(step, in_shardings=(p_sh, o_sh, b_sh),
                       out_shardings=(p_sh, o_sh, NamedSharding(mesh, P())),
                       donate_argnums=(0, 1) if donate else ())

    return compile_for


def zero1_spec(mesh: Mesh, axis: str = "dp"):
    """Spec function sharding each optimizer-state leaf over ``axis``
    (ZeRO stage 1: each data-parallel rank owns 1/N of the moments /
    master weights). Picks the first dimension divisible by the axis
    size; leaves with no divisible dim stay replicated (tiny biases —
    not worth a collective). Use as ``opt_spec_fn`` (and as
    ``param_spec_fn`` too for a ZeRO-3-style fully sharded step)."""
    n = mesh.shape[axis]

    def spec(path, leaf):
        shape = getattr(leaf, "shape", ())
        for i, d in enumerate(shape):
            if d >= n and d % n == 0:
                return P(*([None] * i + [axis]))
        return P()

    return spec


def make_zero1_train_step(loss_fn: Callable, optimizer_update: Callable,
                          mesh: Mesh, data_axis: str = "dp",
                          donate: bool = True, chain: int = 1):
    """DP training with ZeRO-1 optimizer-state sharding: params
    replicated, batch sharded over ``data_axis``, optimizer state
    sharded over ``data_axis`` via :func:`zero1_spec`. Memory per chip
    for optimizer state drops ~Nx (the win that matters for Adam-class
    optimizers where moments are 2x the weights); numerics are
    bit-identical to the replicated step."""
    return make_sharded_train_step(
        loss_fn, optimizer_update, mesh,
        param_spec_fn=None,
        batch_spec=P(data_axis),
        opt_spec_fn=zero1_spec(mesh, data_axis),
        donate=donate, chain=chain)
