"""jax API compatibility for the parallel layer.

``shard_map`` moved from ``jax.experimental.shard_map`` to the
top-level ``jax`` namespace, and its replication-check kwarg renamed
``check_rep`` -> ``check_vma`` along the way. The kernels and sharding
wrappers target the new spelling; this shim keeps the package importable
(and the CPU-mesh test suite runnable) on older installed jax.
"""
from __future__ import annotations

try:  # jax >= 0.6: top-level function, check_vma kwarg
    from jax import shard_map as _shard_map
    _CHECK_KWARG = "check_vma"
except ImportError:  # older jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KWARG = "check_rep"

__all__ = ["shard_map"]


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=None, **kwargs):
    if check_vma is not None:
        kwargs[_CHECK_KWARG] = check_vma
    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
