"""Parallelism over device meshes.

The reference scales via KVStore allreduce (NCCL/ps-lite — SURVEY §2.4);
the TPU-native design scales via jax.sharding: pick a Mesh, annotate
shardings, let XLA insert ICI/DCN collectives. This package is the home
of that machinery:

- mesh.py      — Mesh construction helpers (dp/tp/pp/sp/ep axes)
- collectives.py — named-axis collective wrappers (psum/all_gather/…)
- spmd.py      — sharded train-step builders (the `pjit` path Trainer
                 and the benchmarks use)

These are deliberately *new* surface beyond the reference's API: MXNet
v1.x has no tensor/pipeline/sequence parallelism (SURVEY §2.4); here
they are first-class because the mesh makes them nearly free to expose.
"""
from .mesh import (
    build_mesh, local_mesh, data_parallel_mesh, current_mesh, set_current_mesh,
)
from .collectives import (
    allreduce, allgather, reduce_scatter, ppermute, alltoall, axis_index, axis_size,
)
from .spmd import (
    shard_params, replicate, make_data_parallel_step, make_sharded_train_step,
    zero1_spec, make_zero1_train_step,
)
from .ring_attention import (
    ring_attention, ulysses_attention,
    make_ring_attention_fn, make_ulysses_attention_fn,
)
from .sharded_embedding import (
    make_sharded_embedding_fn, shard_embedding_table,
)
