"""Ring attention & all-to-all (Ulysses-style) sequence parallelism.

Long-context support the reference does not have in any form (SURVEY
§5.7: MXNet v1.x has no fused attention, no sequence/context
parallelism) — first-class here because the TPU mesh makes it natural:

- :func:`ring_attention` — the sequence axis is sharded over a mesh
  axis; K/V chunks rotate around the ring via ``lax.ppermute`` (ICI
  neighbor exchanges) while each device folds incoming chunks into an
  online-softmax accumulator. The fold is a plain jnp einsum +
  online-softmax update (NOT the Pallas flash kernel): it materializes
  one (B, H, C, C) score block per ring step, so peak memory per
  device is O(C^2) per (batch, head) — bounded by the chunk size, not
  the global sequence. ``remat=True`` (default) recomputes the score
  blocks in backward.
- :func:`ulysses_attention` — all-to-all over the mesh axis re-shards
  (B, H, S/P, D) → (B, H/P, S, D) so each device computes full-sequence
  attention for a head subset (single flash kernel call on TPU), then
  all-to-all back. Two collectives per call; cheaper than the ring when
  H ≥ P and the ICI all-to-all bandwidth is good.

Both are differentiable (ppermute/all_to_all have transposes; the ring
uses lax.scan) and are meant to be called INSIDE ``shard_map`` with the
sequence dimension sharded over ``axis_name``. The shard_map wrapper
:func:`make_ring_attention_fn` is the convenience entry the tests and
models use.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

__all__ = ["ring_attention", "ulysses_attention",
           "make_ring_attention_fn", "make_ulysses_attention_fn"]

_NEG_INF = -1e30


def _axis_size_static(axis_name):
    size = lax.axis_size(axis_name) if hasattr(lax, "axis_size") else None
    if size is None or not isinstance(size, int):
        raise ValueError(
            f"static size of mesh axis {axis_name!r} unavailable; pass "
            "axis_size= explicitly")
    return size


def ring_attention(q, k, v, axis_name, causal=False, sm_scale=None,
                   axis_size=None, remat=True, use_flash=None):
    """Blockwise self-attention over a ring of sequence shards.

    Parameters
    ----------
    q, k, v : (B, H, C, D) local sequence chunks; the global sequence
        (S = C * P) is sharded over mesh axis ``axis_name`` in order.
    causal : global causal mask (chunk offsets are accounted for).
    remat : recompute score blocks in backward (flash-style memory).
    use_flash : fold chunks with the Pallas flash kernel + log-sum-exp
        combiner (O(C) per-step memory — the score block never leaves
        VMEM). ``None`` = auto: kernel when the data lives on TPU (or
        kernel-interpret mode is forced), else the pure-jnp
        online-softmax fold (which materializes one (B, H, C, C) score
        block per step and remains the CPU/debug fallback).
    """
    P_ = axis_size if axis_size is not None else _axis_size_static(axis_name)
    b, h, c, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / (d ** 0.5)
    idx = lax.axis_index(axis_name)
    perm = [(j, (j + 1) % P_) for j in range(P_)]

    if use_flash is None:
        # inside shard_map q is a tracer, so this can only consult the
        # backend/interpret flags; make_ring_attention_fn resolves the
        # real mesh-device platform BEFORE wrapping and passes it in
        from ..ops.pallas import _util as _pu
        use_flash = _pu.pallas_ok_for(q)

    if use_flash:
        fold = functools.partial(_fold_flash, q, causal, scale, idx)
    else:
        fold = functools.partial(_fold_jnp, q.astype(jnp.float32), causal,
                                 scale, idx, c)

    def step(carry, t):
        # permute-then-compute: after t rotations this device holds
        # chunk (idx - t) mod P; exactly P-1 neighbor exchanges total
        kc, vc, m, l, acc = carry
        kc, vc = lax.ppermute((kc, vc), axis_name, perm)
        m, l, acc = fold((m, l, acc), kc, vc, (idx - t) % P_)
        return (kc, vc, m, l, acc), None

    if remat:
        fold = jax.checkpoint(fold)
        step = jax.checkpoint(step)

    m0 = jnp.full((b, h, c, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, c, 1), jnp.float32)
    acc0 = jnp.zeros((b, h, c, d), jnp.float32)
    m, l, acc = fold((m0, l0, acc0), k, v, idx)  # own chunk, no comm
    if P_ > 1:
        (_, _, m, l, acc), _ = lax.scan(
            step, (k, v, m, l, acc), jnp.arange(1, P_))

    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = jnp.where(l == 0.0, 0.0, acc / l_safe)
    return out.astype(q.dtype)


def _fold_jnp(qf, causal, scale, idx, c, carry, kc, vc, src):
    """Online-softmax fold of chunk ``src`` (pure jnp: one (B,H,C,C)
    score block per step — the CPU/debug fallback)."""
    m, l, acc = carry
    row = idx * c + lax.broadcasted_iota(jnp.int32, (c, c), 0)
    s = jnp.einsum("bhqd,bhkd->bhqk", qf, kc.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        col = src * c + lax.broadcasted_iota(jnp.int32, (c, c), 1)
        s = jnp.where(col <= row, s, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new)
    l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * alpha + jnp.einsum(
        "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32),
        preferred_element_type=jnp.float32)
    return m_new, l_new, acc_new


def _fold_flash(q, causal, scale, idx, carry, kc, vc, src):
    """Fold chunk ``src`` via the Pallas flash kernel + LSE combiner.

    Per-chunk attention runs entirely in VMEM (O(C) memory); the
    (normalized out, lse) pair merges into the running accumulator with
    the log-sum-exp combiner. Gradients flow through BOTH kernel
    outputs (flash_attention_with_lse carries the dlse cotangent into
    its fused backward).

    Because the kernel's causal offset must be trace-time static but
    ``src`` rotates dynamically, the global causal structure is split
    into three static cases selected by lax.switch: src < idx (fully
    visible — non-causal kernel), src == idx (diagonal — causal
    kernel, offset 0), src > idx (fully masked — zero contribution).
    """
    from ..ops.pallas.flash_attention import flash_attention_with_lse

    m, l, acc = carry
    b, h, c, d = q.shape

    def full_chunk():
        return flash_attention_with_lse(q, kc, vc, scale, False, 0)

    def diag_chunk():
        return flash_attention_with_lse(q, kc, vc, scale, True, 0)

    def masked_chunk():
        return (jnp.zeros((b, h, c, d), q.dtype),
                jnp.full((b, h, c), _NEG_INF, jnp.float32))

    if causal:
        case = jnp.where(src < idx, 0, jnp.where(src == idx, 1, 2))
        o_c, lse_c = lax.switch(case, (full_chunk, diag_chunk, masked_chunk))
    else:
        o_c, lse_c = full_chunk()

    lse_c = lse_c[..., None]  # (b, h, c, 1)
    m_new = jnp.maximum(m, lse_c)
    # exp(sentinel - sentinel) = 1 would resurrect empty accumulators:
    # gate each term on its side having seen at least one real score
    alpha = jnp.where(m > _NEG_INF / 2, jnp.exp(m - m_new), 0.0)
    beta = jnp.where(lse_c > _NEG_INF / 2, jnp.exp(lse_c - m_new), 0.0)
    l_new = l * alpha + beta
    acc_new = acc * alpha + o_c.astype(jnp.float32) * beta
    return m_new, l_new, acc_new


def ulysses_attention(q, k, v, axis_name, causal=False, sm_scale=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern).

    q, k, v : (B, H, C, D) sequence chunks, H divisible by the axis
    size. Re-shards to (B, H/P, S, D), runs full-sequence attention
    locally (Pallas flash kernel on TPU via the op-layer impl), and
    re-shards back.
    """
    qg = lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2, tiled=True)
    kg = lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2, tiled=True)
    vg = lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2, tiled=True)
    from ..ndarray.op_impl_nn import flash_attention_op

    og = flash_attention_op(qg, kg, vg, causal=causal, sm_scale=sm_scale)
    return lax.all_to_all(og, axis_name, split_axis=2, concat_axis=1,
                          tiled=True)


def _seq_sharded_wrapper(fn, mesh, axis_name, **kw):
    from ._compat import shard_map

    spec = P(None, None, axis_name, None)
    wrapped = shard_map(
        functools.partial(fn, axis_name=axis_name, **kw),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return wrapped


def make_ring_attention_fn(mesh, axis_name="sp", causal=False,
                           sm_scale=None, remat=True, use_flash=None):
    """shard_map-wrapped ring attention over ``mesh[axis_name]``.

    Returns fn(q, k, v) on GLOBAL (B, H, S, D) arrays with S sharded
    over ``axis_name``; jit/grad-compatible.
    """
    if use_flash is None:
        # resolve on the mesh's REAL devices (inside shard_map only the
        # backend is visible): a CPU-device mesh in a TPU-backend
        # process must take the jnp fold, not crash in Mosaic
        from ..ops.pallas._util import interpret_mode, pallas_enabled
        use_flash = pallas_enabled() and (
            interpret_mode() or
            all(d.platform == "tpu" for d in mesh.devices.flat))
    return _seq_sharded_wrapper(
        ring_attention, mesh, axis_name, causal=causal, sm_scale=sm_scale,
        axis_size=int(mesh.shape[axis_name]), remat=remat,
        use_flash=use_flash)


def make_ulysses_attention_fn(mesh, axis_name="sp", causal=False,
                              sm_scale=None):
    """shard_map-wrapped Ulysses attention over ``mesh[axis_name]``."""
    return _seq_sharded_wrapper(
        ulysses_attention, mesh, axis_name, causal=causal, sm_scale=sm_scale)
