"""Monitor — per-op output statistics hooks (python/mxnet/monitor.py).

The reference installs C-level output callbacks on executors
(MXExecutorSetMonitorCallback); here the imperative dispatch layer calls
``Monitor.tick_array`` when installed (the Gluon path uses Block hooks
— see gluon/block.py register_forward_hook)."""
from __future__ import annotations

import logging
import re
from collections import OrderedDict

from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor:
    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def asum_stat(x):
                return x.norm() / (x.size ** 0.5)
            stat_func = asum_stat
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def stat_helper(self, name, arr):
        if not self.activated or not self.re_prog.match(str(name)):
            return
        self.queue.append((self.step, str(name), self.stat_func(arr)))

    def tic(self):
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True

    def toc(self):
        if not self.activated:
            return []
        self.activated = False
        res = []
        queue = sorted(self.queue, key=lambda x: x[1]) if self.sort else self.queue
        for n, k, v_list in queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            s = ""
            for v in v_list:
                assert isinstance(v, NDArray)
                if v.shape == (1,) or v.shape == ():
                    s += str(v.asscalar()) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        self.queue = []
        return res

    def toc_print(self):
        res = self.toc()
        for n, k, v in res:
            logging.info("Batch: %7d %30s %s", n, k, v)
