"""Sampled production-traffic capture + deterministic replay.

``CaptureStore`` records a head-sampled fraction of ADMITTED requests
at their completion point (engine ``_dispatch`` / decode ``_leave``)
into a bounded, crash-safe on-disk corpus:

- one record per sampled request: prompt tokens (or only their digest,
  per ``MXNET_TPU_CAPTURE_PAYLOAD``), sampling params + seed, model id
  and version, tenant/class, arrival time (monotonic AND wall), the
  outcome, the output's byte digest, total latency and the per-stage
  critical-path breakdown;
- records are :func:`~.wire.wire_encode` frames (the serving stack's
  one typed codec — ndarrays ride raw, nothing is stringified) inside
  length+CRC-framed append-only segment files, following the
  ``telemetry/history.py`` segment discipline: seq-numbered segments
  rotate at a size bound, sealed segments are evicted oldest-first
  when the corpus exceeds ``MXNET_TPU_CAPTURE_MAX_MB``, and a torn
  tail (crash mid-append) is skipped and COUNTED on reload, never a
  load failure;
- synthetic canary probes (trace ids minted ``canary-…``, billed
  ``traffic="synthetic"``) are excluded BEFORE sampling, so a corpus
  is real traffic only and loadgen's ledger reconciliation still
  balances;
- ``mxnet_tpu_capture_*`` metric families + the ``/capture`` summary
  body exist only while capture is enabled (``MXNET_TPU_CAPTURE=0``
  builds nothing: no thread, no families, no files).

Because seeded sampling (``(seed, position)`` PRNG) makes every decode
byte-reproducible, a captured corpus is an offline correctness oracle:
:func:`replay` feeds it back through a live engine/router — original
inter-arrival pacing or a ``speed`` multiplier — and asserts each
replayed output against the recording, reporting every divergence
with the replayed request's own stage breakdown. That is the
regression harness for kernel/scheduler/model changes, and the corpus
the shadow-diff validator (:mod:`~.shadow`) shares its digest
contract with.

Two comparison regimes, because the two output kinds have different
reproducibility physics:

- integer outputs (decode token streams) must be BYTE-IDENTICAL to
  the captured digest — the seed owns the randomness, so any flip is
  a real regression;
- float outputs (pooled encoder embeddings) are bitwise-stable only
  for an identical PACKING: the same request placed at a different
  lane offset inside a packed row regroups the kernel's reductions
  and moves the result by ~1 ulp (~1e-7). Since replay cannot
  reproduce the original co-tenants of a row, small float outputs
  ride in the record (``output_vals``) and replay accepts them within
  ``allclose(rtol=atol=1e-5)`` — two orders looser than packing
  noise, four orders tighter than any real numeric regression. A
  bitwise digest match still short-circuits as the fast path.
"""
from __future__ import annotations

import hashlib
import os
import re
import struct
import threading
import time
import zlib

import numpy as np

from .. import envvars
from ..telemetry import events as _events
from ..telemetry.registry import REGISTRY as _REGISTRY
from .wire import wire_decode, wire_encode

__all__ = ["CaptureStore", "output_digest", "load_corpus", "replay"]

#: per-record frame header: payload length + CRC32 of the payload
_REC_HDR = struct.Struct("<II")
#: refuse absurd record lengths on load (a corrupt header must not
#: allocate gigabytes) — generous: prompts are token arrays, not blobs
_REC_MAX = 64 << 20

_SEG_RE = re.compile(r"corpus-(\d+)\.seg$")


def output_digest(out):
    """Canonical 16-hex-char digest of one request's output: dtype +
    shape + raw bytes of the C-contiguous array. Decode outputs are
    int32 token sequences (seeded sampling makes them byte-exact on
    replay); encoder outputs are the pooled float arrays (bitwise
    stable only for an identical packing — see the module docstring
    for the tolerance regime replay applies). None digests to
    ``"none"`` so failed requests still compare."""
    if out is None:
        return "none"
    arr = np.ascontiguousarray(np.asarray(out))
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(arr.tobytes())
    return h.hexdigest()[:16]


#: float outputs larger than this (elements) are digest-only — the
#: corpus is a traffic record, not an activation dump
_VALS_MAX = 4096


def _capture_vals(out, payload):
    """The float-output payload for tolerance replay: the output array
    itself, when it is float-typed, small, and the corpus carries full
    payloads (digest-only corpora are not replayable anyway)."""
    if out is None or payload != "tokens":
        return None
    arr = np.asarray(out)
    if arr.dtype.kind != "f" or arr.size > _VALS_MAX:
        return None
    return np.ascontiguousarray(arr)


def is_synthetic(trace_id):
    """True for synthetic canary traffic — the request-level face of
    the ``traffic="synthetic"`` billing tag: canary probes mint their
    trace ids with the ``canary`` prefix (``telemetry/canary.py``) and
    must never enter a capture corpus."""
    return bool(trace_id) and str(trace_id).startswith("canary")


class CaptureStore:
    """Bounded crash-safe corpus of sampled request records.

    Built by an engine's ``start()`` only when ``MXNET_TPU_CAPTURE``
    is on; ``record_request`` is called inline at the completion point
    (one dict + one wire_encode per SAMPLED request — no extra
    thread). With no ``MXNET_TPU_CAPTURE_DIR`` the corpus lives in
    memory (bounded by the same byte budget) — tests and single-process
    replay work without touching disk; a directory makes it durable
    and shareable across processes."""

    def __init__(self, owner_id, dir=None, rate=None, max_mb=None,
                 payload=None):
        self.owner_id = str(owner_id)
        self.dir = (str(dir) if dir is not None
                    else envvars.get("MXNET_TPU_CAPTURE_DIR"))
        rate = (float(rate) if rate is not None
                else envvars.get("MXNET_TPU_CAPTURE_RATE"))
        self.rate = min(1.0, max(0.0, rate))
        self.max_bytes = (float(max_mb) if max_mb is not None
                          else envvars.get("MXNET_TPU_CAPTURE_MAX_MB")
                          ) * 1024 * 1024
        # rotation bound derived from the budget: eviction works on
        # whole sealed segments, so ~8 per budget keeps it incremental
        self.segment_bytes = max(4096.0, self.max_bytes / 8.0)
        payload = (payload if payload is not None
                   else envvars.get("MXNET_TPU_CAPTURE_PAYLOAD"))
        self.payload = ("digest" if str(payload).lower() == "digest"
                        else "tokens")
        self._lock = threading.Lock()
        self._accum = 0.0           # deterministic head-sampling credit
        self._fh = None             # active segment [fh, path, size]
        self._seq = None
        self._mem = []              # dir-less fallback: raw frames
        self._mem_bytes = 0
        self.written = 0            # records this store appended
        self.write_errors = 0
        self._first_wall = None
        self._last_wall = None
        c = _REGISTRY.counter(
            "mxnet_tpu_capture_requests_total",
            "traffic-capture sampling outcomes per completed request: "
            "sampled (recorded), skipped (head-sampled out), synthetic "
            "(canary traffic, excluded), error (corpus write failed)",
            ("owner", "result"))
        self._c = {r: c.labels(owner=self.owner_id, result=r)
                   for r in ("sampled", "skipped", "synthetic", "error")}
        self._c_bytes = _REGISTRY.counter(
            "mxnet_tpu_capture_bytes_total",
            "corpus bytes appended (framed record payloads)",
            ("owner",)).labels(owner=self.owner_id)
        _REGISTRY.gauge(
            "mxnet_tpu_capture_corpus_bytes",
            "current corpus size in bytes (sealed + active segments, "
            "after eviction)", ("owner",)) \
            .labels(owner=self.owner_id).set_function(self.corpus_bytes)
        _REGISTRY.gauge(
            "mxnet_tpu_capture_sample_rate",
            "configured head-sampling rate (0..1)", ("owner",)) \
            .labels(owner=self.owner_id).set(self.rate)
        _events.emit("capture_start", owner=self.owner_id,
                     dir=self.dir, rate=self.rate, payload=self.payload)

    # -- sampling ----------------------------------------------------------
    def should_sample(self, trace_id=None):
        """The head-based decision: made per admitted request, before
        (and independent of) its outcome. Synthetic canary traffic is
        excluded outright; real traffic is sampled deterministically
        at ``rate`` by exact credit accumulation (rate 0.25 records
        every 4th request — no RNG, so tests and cross-process
        corpora are reproducible)."""
        if is_synthetic(trace_id):
            self._c["synthetic"].inc()
            return False
        with self._lock:
            self._accum += self.rate
            if self._accum >= 1.0:
                self._accum -= 1.0
                return True
        self._c["skipped"].inc()
        return False

    # -- recording ---------------------------------------------------------
    def record_request(self, req, out, outcome, total_ms, model=None,
                       version=None, engine_id=None):
        """Build + append one record for a completed (or failed)
        request, if the head sampler elects it. Called inline on the
        engine worker at the completion point, where outcome, cost and
        breakdown are all known."""
        if not self.should_sample(req.trace_id):
            return False
        now = time.monotonic()
        tokens = getattr(req, "tokens", None)
        decode = None
        if hasattr(req, "seed"):        # DecodeRequest
            decode = {"max_new_tokens": int(req.max_new_tokens),
                      "eos_id": (int(req.eos_id)
                                 if req.eos_id is not None else None),
                      "temperature": float(req.temperature),
                      "top_k": int(req.top_k),
                      "top_p": float(req.top_p),
                      "seed": int(req.seed)}
        rec = {"v": 1,
               "trace_id": req.trace_id,
               "engine_id": str(engine_id) if engine_id else None,
               "model": str(model) if model is not None else None,
               "version": str(version) if version is not None else None,
               "tenant": req.tenant,
               "tenant_class": req.tenant_class,
               # arrival on BOTH clocks: monotonic deltas drive replay
               # pacing; wall anchors the corpus in operator time
               "arrival_mono": float(req.t_submit),
               "arrival_wall": time.time() - (now - req.t_submit),  # mxlint: disable=wall-clock-delta
               "prompt_len": int(tokens.size) if tokens is not None
               else 0,
               "tokens": (np.asarray(tokens, np.int32)
                          if self.payload == "tokens"
                          and tokens is not None else None),
               "prompt_digest": output_digest(tokens),
               "decode": decode,
               "outcome": str(outcome),
               "output_digest": output_digest(out),
               # small FLOAT outputs ride along: packed-row lane
               # placement moves fp results by ~1 ulp, so replay
               # needs the values (not just the digest) to compare
               # within tolerance; int token streams stay digest-only
               "output_vals": _capture_vals(out, self.payload),
               "output_len": (int(np.asarray(out).size)
                              if out is not None else 0),
               "total_ms": float(total_ms),
               "breakdown": getattr(req.future, "breakdown", None)}
        return self.append(rec)

    def append(self, rec):
        """Frame + append one record dict (the typed wire codec, so
        token arrays ride as raw int32 — and reload is bit-exact)."""
        payload = wire_encode(rec)
        frame = _REC_HDR.pack(len(payload),
                              zlib.crc32(payload) & 0xFFFFFFFF) + payload
        with self._lock:
            ok = self._write(frame)
            if ok:
                self.written += 1
                wall = rec.get("arrival_wall")
                if wall is not None:
                    if self._first_wall is None:
                        self._first_wall = wall
                    self._last_wall = wall
        if ok:
            self._c["sampled"].inc()
            self._c_bytes.inc(len(frame))
        else:
            self.write_errors += 1
            self._c["error"].inc()
        return ok

    def _write(self, frame):
        if self.dir is None:
            self._mem.append(frame)
            self._mem_bytes += len(frame)
            while self._mem_bytes > self.max_bytes and len(self._mem) > 1:
                self._mem_bytes -= len(self._mem.pop(0))
            return True
        try:
            if self._fh is None:
                self._open_segment()
            fh, _path, size = self._fh
            fh.write(frame)
            fh.flush()
        except OSError:
            return False        # disk trouble must not fail serving
        self._fh[2] = size + len(frame)
        if self._fh[2] >= self.segment_bytes:
            try:
                fh.close()
            except OSError:
                pass
            self._fh = None     # sealed: now evictable
            self._enforce_disk()
        return True

    def _open_segment(self):
        os.makedirs(self.dir, exist_ok=True)
        if self._seq is None:
            self._seq = 1 + max(
                [_seg_seq(p) for p in os.listdir(self.dir)
                 if p.endswith(".seg")] or [0])
        path = os.path.join(self.dir, f"corpus-{self._seq:08d}.seg")
        self._seq += 1
        self._fh = [open(path, "ab"), path, 0]

    def _segments(self):
        out = []
        if self.dir is None or not os.path.isdir(self.dir):
            return out
        for name in os.listdir(self.dir):
            if not name.endswith(".seg"):
                continue
            path = os.path.join(self.dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, path))
        return out

    def _enforce_disk(self):
        """Budget sweep over SEALED segments, oldest first — the
        active handle keeps writing, exactly the history-store
        discipline (a fresh segment is never deleted)."""
        active = {self._fh[1]} if self._fh is not None else set()
        segs = sorted(s for s in self._segments() if s[2] not in active)
        total = sum(s[1] for s in segs)
        for _mtime, size, path in segs:
            if total <= self.max_bytes:
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            _events.emit("capture_evict", owner=self.owner_id,
                         path=os.path.basename(path), bytes=size)

    # -- reading -----------------------------------------------------------
    def corpus_bytes(self):
        with self._lock:
            if self.dir is None:
                return self._mem_bytes
            return sum(s[1] for s in self._segments()) \
                + (self._fh[2] if self._fh is not None else 0)

    def records(self):
        """Every readable record in arrival order (in-memory frames or
        the on-disk segments). Returns ``(records, skipped)`` where
        ``skipped`` counts torn/corrupt frames tolerated on load."""
        with self._lock:
            if self.dir is None:
                records, skipped = [], 0
                for frame in self._mem:
                    rec = _decode_frame(frame)
                    if rec is None:
                        skipped += 1
                    else:
                        records.append(rec)
                return records, skipped
            if self._fh is not None:
                try:
                    self._fh[0].flush()
                except OSError:
                    pass
        return load_corpus(self.dir)

    def summary(self):
        """The ``/capture`` exposition body (and the router's per-seat
        merge input): configuration + corpus shape at a glance."""
        with self._lock:
            segs = self._segments()
            active = self._fh[1] if self._fh is not None else None
            written = self.written
            first, last = self._first_wall, self._last_wall
        now = time.time()
        return {"owner": self.owner_id, "enabled": True,
                "dir": self.dir, "rate": self.rate,
                "payload": self.payload,
                "records_written": written,
                "write_errors": self.write_errors,
                "corpus_bytes": self.corpus_bytes(),
                "segments": len(segs) + (1 if self.dir is None
                                         and self._mem else 0),
                "active_segment": (os.path.basename(active)
                                   if active else None),
                "max_mb": round(self.max_bytes / 1024 / 1024, 3),
                "oldest_wall": first, "newest_wall": last,
                # corpus age is a delta between WALL stamps by design:
                # records may come from other processes, whose
                # monotonic clocks don't compare
                "age_s": (round(now - first, 3)  # mxlint: disable=wall-clock-delta
                          if first is not None else None)}

    def close(self):
        """Seal the active segment (flush + close). The corpus stays
        readable on disk (or in memory)."""
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh[0].close()
                except OSError:
                    pass
                self._fh = None
        _events.emit("capture_close", owner=self.owner_id,
                     records=self.written)


def _seg_seq(name):
    m = _SEG_RE.search(name)
    return int(m.group(1)) if m else 0


def _decode_frame(frame):
    if len(frame) < _REC_HDR.size:
        return None
    n, crc = _REC_HDR.unpack_from(frame)
    payload = frame[_REC_HDR.size:_REC_HDR.size + n]
    if len(payload) != n or zlib.crc32(payload) & 0xFFFFFFFF != crc:
        return None
    try:
        return wire_decode(payload)
    except ValueError:
        return None


def load_corpus(dir):
    """Load every record from a corpus directory, segments in sequence
    order, records in append order. Crash-tolerant: a torn tail (or a
    corrupt frame — bad CRC, bad length, undecodable payload) ends
    THAT segment's scan and is counted, never raised. Returns
    ``(records, skipped)``."""
    records, skipped = [], 0
    if not dir or not os.path.isdir(dir):
        return records, skipped
    names = sorted((n for n in os.listdir(dir) if n.endswith(".seg")),
                   key=_seg_seq)
    for name in names:
        try:
            with open(os.path.join(dir, name), "rb") as fh:
                buf = fh.read()
        except OSError:
            skipped += 1
            continue
        pos = 0
        while pos + _REC_HDR.size <= len(buf):
            n, crc = _REC_HDR.unpack_from(buf, pos)
            start = pos + _REC_HDR.size
            if n > _REC_MAX or start + n > len(buf):
                break           # torn tail / corrupt length
            payload = buf[start:start + n]
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                break           # corrupt frame: resync is hopeless
            try:
                records.append(wire_decode(payload))
            except ValueError:
                break
            pos = start + n
        if pos < len(buf):
            # anything after the last clean frame — a corrupt frame or
            # a torn tail, even one shorter than a header — counts
            skipped += 1
    return records, skipped


# -- deterministic replay ---------------------------------------------------
def _submit_record(target, rec):
    """Re-submit one captured record against a live target — a
    :class:`~.engine.ServingEngine`, :class:`~.decode.DecodeEngine` or
    :class:`~.router.ServingRouter` (their decode-parameter submit
    surfaces are intentionally congruent). The captured seed rides
    along, so a seeded decode replays byte-identically."""
    tokens = np.asarray(rec["tokens"], np.int32)
    decode = rec.get("decode")
    common = dict(model_id=rec.get("model"), tenant=rec.get("tenant"),
                  tenant_class=rec.get("tenant_class"))
    if decode:
        sp = getattr(target, "submit_payload", None)
        if sp is not None:      # decode engine: payload-dict surface
            fut, _streamed = sp(dict(decode, tokens=tokens, **common))
            return fut
        return target.submit(tokens,
                             max_new_tokens=decode.get("max_new_tokens"),
                             eos_id=decode.get("eos_id"),
                             temperature=decode.get("temperature"),
                             top_k=decode.get("top_k"),
                             top_p=decode.get("top_p"),
                             seed=decode.get("seed"), **common)
    return target.submit(tokens, **common)


def replay(records, target, speed=None, timeout_s=60.0):
    """Deterministic re-execution: feed captured records back through
    ``target`` in arrival order and assert each seeded stream is
    byte-identical to its captured digest (float outputs: within the
    packing-noise tolerance — module docstring).

    ``speed`` — None/0 replays as fast as the target admits; ``1.0``
    reproduces the original inter-arrival pacing, ``2.0`` runs it
    twice as fast, etc.

    Returns the divergence report::

        {"replayed", "matched", "matched_bitwise",
         "matched_within_tol", "divergences": [{trace_id, model,
         expected, got, max_abs_diff, captured_ms, replay_ms,
         breakdown}, ...],
         "errors": [{trace_id, error}], "skipped": {...}, "wall_s"}

    Only ``completed`` records with a recorded prompt payload are
    replayable (digest-only corpora — ``MXNET_TPU_CAPTURE_PAYLOAD=
    digest`` — and shed/failed requests are counted in ``skipped``).
    Divergences carry the REPLAYED request's own stage breakdown, so
    a regression is immediately attributable (which stage of the
    diverging request's critical path changed)."""
    speed = float(speed) if speed else 0.0
    skipped = {"no_payload": 0, "not_completed": 0}
    runnable = []
    for rec in records:
        if rec.get("outcome") != "completed":
            skipped["not_completed"] += 1
        elif rec.get("tokens") is None:
            skipped["no_payload"] += 1
        else:
            runnable.append(rec)
    runnable.sort(key=lambda r: r.get("arrival_mono") or 0.0)
    t_wall0 = time.monotonic()
    inflight = []
    prev_arrival = None
    for rec in runnable:
        arrival = rec.get("arrival_mono")
        if speed > 0 and prev_arrival is not None \
                and arrival is not None:
            gap = (arrival - prev_arrival) / speed
            if gap > 0:
                time.sleep(min(gap, 60.0))
        if arrival is not None:
            prev_arrival = arrival
        t0 = time.monotonic()
        try:
            fut = _submit_record(target, rec)
        except Exception as e:
            inflight.append((rec, None, t0, e))
            continue
        inflight.append((rec, fut, t0, None))
    divergences, errors = [], []
    matched = bitwise = within_tol = 0
    for rec, fut, t0, exc in inflight:
        if exc is None:
            try:
                out = fut.result(timeout=timeout_s)
            except Exception as e:
                exc = e
        if exc is not None:
            errors.append({"trace_id": rec.get("trace_id"),
                           "error": f"{type(exc).__name__}: {exc}"})
            continue
        got = output_digest(out)
        replay_ms = (time.monotonic() - t0) * 1e3
        if got == rec.get("output_digest"):
            matched += 1
            bitwise += 1
            continue
        # float fallback: packing noise moves fp outputs by ~1 ulp
        # (module docstring) — compare the recorded VALUES within a
        # tolerance far above that and far below any real regression
        vals = rec.get("output_vals")
        max_diff = None
        if vals is not None and out is not None:
            got_arr = np.asarray(out)
            vals = np.asarray(vals)
            if got_arr.shape == vals.shape \
                    and got_arr.dtype.kind == "f":
                max_diff = float(np.max(np.abs(
                    got_arr.astype(np.float64)
                    - vals.astype(np.float64)))) if vals.size else 0.0
                if np.allclose(got_arr, vals, rtol=1e-5, atol=1e-5):
                    matched += 1
                    within_tol += 1
                    continue
        divergences.append({
            "trace_id": rec.get("trace_id"),
            "model": rec.get("model"),
            "expected": rec.get("output_digest"),
            "got": got,
            "max_abs_diff": max_diff,
            "captured_ms": rec.get("total_ms"),
            "replay_ms": round(replay_ms, 3),
            # the REPLAYED request's critical path — where the
            # diverging request spent its time under the new code
            "breakdown": getattr(fut, "breakdown", None)})
    report = {"replayed": len(inflight), "matched": matched,
              "matched_bitwise": bitwise,
              "matched_within_tol": within_tol,
              "divergences": divergences, "errors": errors,
              "skipped": skipped, "speed": speed or None,
              "wall_s": round(time.monotonic() - t_wall0, 3)}
    _events.emit("capture_replay", replayed=report["replayed"],
                 matched=matched, divergences=len(divergences),
                 errors=len(errors))
    return report


def merge_summaries(parts, owner=None):
    """The router's fleet ``/capture`` body: per-seat summaries under
    ``engines`` plus fleet totals (records, bytes, write errors).
    ``parts`` is ``[(engine_id, summary_or_None), ...]``; seats
    without capture (disabled, old peers) land in ``missing``."""
    engines, missing = {}, []
    records = bytes_total = errors = 0
    for eid, summ in parts:
        if not summ:
            missing.append(eid)
            continue
        engines[str(eid)] = summ
        records += int(summ.get("records_written") or 0)
        bytes_total += int(summ.get("corpus_bytes") or 0)
        errors += int(summ.get("write_errors") or 0)
    out = {"owner": owner, "enabled": bool(engines),
           "engines": engines,
           "fleet": {"records_written": records,
                     "corpus_bytes": bytes_total,
                     "write_errors": errors}}
    if missing:
        out["missing"] = missing
    return out
