"""Autoregressive decode serving: iteration-level continuous batching
over a paged KV cache, with streamed tokens.

The encoder :class:`~.engine.ServingEngine` re-forms a batch per
REQUEST; a decode server must re-form it per TOKEN. The
:class:`DecodeEngine` worker runs the Orca-style loop:

1. **Join at any iteration boundary.** Queued prompts are admitted
   between decode iterations — at most
   ``MXNET_TPU_DECODE_PREFILLS_PER_ITER`` prefills in flight per
   boundary. Prompts are NOT prefilled in one dense step: they are cut
   into kernel-sized chunks (``batcher.PrefillChunks`` buckets) and
   interleaved at iteration boundaries under a per-iteration token
   budget (``MXNET_TPU_DECODE_PREFILL_BUDGET``), so a 2k-token prompt
   never stalls the running batch for more than one chunk — the
   long-prompt TTFT vs everyone-else inter-token-p99 trade, both
   measured (``0`` restores whole-prompt dense prefill, the A/B
   baseline). Admission first asks the pool for a cached PREFIX match
   (``MXNET_TPU_KV_PREFIX``): full prompt-prefix pages computed by an
   earlier same-prefix request attach read-only (refcounted owner
   sets, copy-on-write at the divergence page), and the chunk loop
   starts at the first unmatched token — prefix hits cut both TTFT
   and device-s/1k-tokens. Admission reserves each request's
   WORST-CASE page budget up front, so the decode loop can never
   deadlock on an exhausted pool mid-generation — a join that doesn't
   fit is deferred (front of queue), not failed.
2. **One decode iteration** advances every live sequence by one token:
   a single compiled step over the (rows × table-width) bucket
   (``batcher.DecodeSlots``), each row reading its own KV history
   through its page-table row (``ops.pallas.flash_attention.
   paged_flash_attention``) and writing its new K/V slot in place
   (donated buffers — ``decode_model.py``). Rows are numerically
   independent, so joining/leaving neighbors never change a sequence's
   tokens (the solo-parity golden).
3. **Leave on EOS / max-tokens**, KV pages recycled the same
   iteration; every generated token is pushed to the request's future
   as a streamed part (``InferenceFuture.stream()``) the moment it
   exists — inter-token latency is a first-class SLI
   (``mxnet_tpu_serving_inter_token_latency_ms`` + the default
   ``decode_inter_token`` LatencySLO).

Token selection is greedy argmax by default (deterministic — the
solo-parity lever); a request may carry ``temperature``/``top_k``/
``top_p``/``seed`` (validated at submit, carried in wire SUBMIT
frames, HTTP ``/submit`` and the router's HA journal), and the PRNG
key is a pure function of (seed, position) — a stream replayed on
another seat after failover resamples byte-identically.

``iteration_level=False`` degrades the scheduler to classic STATIC
batching (joins only when the batch has fully drained, whole-prompt
dense prefill) — the bench leg's A/B baseline, kept deliberately so
the win stays measurable.
"""
from __future__ import annotations

import itertools
import os
import threading
import time

import numpy as np

from .. import compile_cache, envvars
from ..telemetry import attribution as _attribution
from ..telemetry import events as _events
from ..telemetry import incidents as _incidents
from ..telemetry import profiling as _profiling
from ..telemetry import recorder as _recorder
from ..telemetry.registry import REGISTRY as _REGISTRY
from . import tenancy
from .batcher import DecodeSlots, PrefillChunks
from .engine import _SUBMIT_ERROR_STATUS
from .kvcache import PagedKVPool
from .metrics import (CostLedger, DecodeStats, ServingStats,
                      exemplar_gate, slow_exemplar)
from .queue import (DeadlineExceededError, EngineStoppedError,
                    QueueFullError, Request, RequestQueue,
                    RequestTooLongError, ServingError,
                    UnknownModelError, validate_sampling)

__all__ = ["DecodeEngine", "DecodeRequest"]

_engine_seq = itertools.count()


class DecodeRequest(Request):
    """One generation request: the prompt plus decode bookkeeping —
    generated tokens so far, the sequence's write position, chunked-
    prefill progress, sampling parameters, and the per-token timing
    stamps the inter-token SLI reads."""

    __slots__ = ("max_new_tokens", "eos_id", "stream", "generated",
                 "pos", "t_first", "t_last", "device_s", "prompt_len",
                 "temperature", "top_k", "top_p", "seed",
                 "prefill_pos", "reused_tokens")

    def __init__(self, tokens, max_new_tokens, eos_id=None, stream=False,
                 deadline_ms=None, trace_id=None, parent_span_id=None,
                 temperature=0.0, top_k=0, top_p=1.0, seed=0,
                 tenant=None, tenant_class=None, model_id=None):
        super().__init__(tokens, None, deadline_ms, trace_id=trace_id,
                         parent_span_id=parent_span_id, tenant=tenant,
                         tenant_class=tenant_class, model_id=model_id)
        self.prompt_len = int(self.tokens.size)
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_id = int(eos_id) if eos_id is not None else None
        self.stream = bool(stream)
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed)
        self.generated = []
        self.pos = self.prompt_len     # where the NEXT token's KV goes
        self.prefill_pos = 0           # prompt tokens already in pages
        self.reused_tokens = 0         # of them, served by prefix reuse
        self.t_first = self.t_last = None
        self.device_s = 0.0            # amortized decode wall share


class DecodeEngine:
    """Continuous-batching decode server around one paged-KV LM.

    Parameters
    ----------
    model : the decode contract (``decode_model.PagedCausalLM`` or
        anything matching it): ``spec`` (KV geometry),
        ``prefill(caches, ids, length, phys, off)`` and
        ``decode_step(caches, ids, positions, tables)``.
    prefill_bucket_lens : padded prompt-length buckets (ascending);
        a longer prompt is rejected at submit.
    max_rows : decode slot cap (default ``MXNET_TPU_DECODE_ROWS``).
    page_size / n_pages : KV pool geometry (``MXNET_TPU_KV_PAGE*``).
    max_new_tokens : default generation cap
        (``MXNET_TPU_DECODE_MAX_NEW_TOKENS``).
    eos_id : default end-of-sequence token id (None = generate to the
        cap).
    iteration_level : True (default) = Orca-style joins at iteration
        boundaries; False = static cohort batching (the A/B baseline —
        whole-prompt dense prefill, no prefix reuse).
    engine_id : metric/scoreboard label, as on ``ServingEngine``.
    prefill_budget : prompt tokens prefilled per iteration boundary
        (``MXNET_TPU_DECODE_PREFILL_BUDGET``); 0 = whole-prompt dense
        prefill (the chunked-prefill A/B baseline).
    prefix_cache / prefix_pages : prefix-KV reuse knobs forwarded to
        the pool (``MXNET_TPU_KV_PREFIX`` / ``_PAGES``); reuse needs
        chunked prefill (the dense prefill step cannot resume
        mid-prompt) and is forced off without it.
    temperature / top_k / top_p : engine-default sampling params for
        requests that carry none (``MXNET_TPU_DECODE_TEMPERATURE`` /
        ``_TOP_K`` / ``_TOP_P``; temperature 0 = greedy argmax).
    """

    def __init__(self, model, prefill_bucket_lens=(16, 64, 256),
                 max_rows=None, page_size=None, n_pages=None,
                 max_queue_depth=256, default_deadline_ms=None,
                 max_new_tokens=None, eos_id=None, iteration_level=True,
                 stats_window=4096, engine_id=None,
                 prefills_per_iter=None, prefill_budget=None,
                 prefix_cache=None, prefix_pages=None,
                 temperature=None, top_k=None, top_p=None,
                 model_id=None, model_version=None):
        self._model = model
        spec = dict(model.spec)
        self.engine_id = str(engine_id) if engine_id is not None \
            else f"d{os.getpid():x}-{next(_engine_seq)}"
        # a decode engine hosts ONE paged-KV LM (the page pool is
        # sized to its geometry) but still names it, so model_id rides
        # its wire frames / journal entries / bills exactly as on the
        # multi-model encoder engine — and a request addressed to a
        # model this engine does not host is a typed 404, not silence
        self.model_id = (str(model_id) if model_id is not None
                         else tenancy.default_model_id())
        self.model_version = (str(model_version)
                              if model_version is not None else "v0")
        self.max_len = int(spec["max_len"])
        lens = sorted(set(int(b) for b in prefill_bucket_lens))
        if not lens or lens[0] < 1:
            raise ValueError(
                f"bad prefill_bucket_lens {prefill_bucket_lens!r}")
        self.prefill_bucket_lens = tuple(lens)
        self._max_rows = int(max_rows if max_rows is not None
                             else envvars.get("MXNET_TPU_DECODE_ROWS"))
        self._default_max_new = int(
            max_new_tokens if max_new_tokens is not None
            else envvars.get("MXNET_TPU_DECODE_MAX_NEW_TOKENS"))
        self._default_eos = eos_id
        self._iteration_level = bool(iteration_level)
        self._prefills_per_iter = max(1, int(
            prefills_per_iter if prefills_per_iter is not None
            else envvars.get("MXNET_TPU_DECODE_PREFILLS_PER_ITER")))
        self._default_deadline_ms = default_deadline_ms
        t, k, p, _ = validate_sampling(
            temperature if temperature is not None
            else envvars.get("MXNET_TPU_DECODE_TEMPERATURE"),
            top_k if top_k is not None
            else envvars.get("MXNET_TPU_DECODE_TOP_K"),
            top_p if top_p is not None
            else envvars.get("MXNET_TPU_DECODE_TOP_P"), None)
        self._default_temp, self._default_top_k, self._default_top_p = \
            t, k, p
        budget = int(prefill_budget if prefill_budget is not None
                     else envvars.get("MXNET_TPU_DECODE_PREFILL_BUDGET"))
        # chunked prefill rides the iteration loop; the static cohort
        # scheduler (the A/B baseline) keeps whole-prompt dense prefill
        self._prefill_budget = budget if self._iteration_level else 0
        self.pool = PagedKVPool(
            spec["n_layers"], spec["n_heads"], spec["head_dim"],
            page_size=page_size, n_pages=n_pages,
            engine_id=self.engine_id,
            # the dense prefill step recomputes the WHOLE prompt and
            # rewrites its pages — it cannot start mid-sequence, so
            # prefix reuse is only sound on the chunked path
            prefix_cache=(False if self._prefill_budget <= 0
                          else prefix_cache),
            prefix_pages=prefix_pages)
        self._slots = DecodeSlots(
            max_rows=self._max_rows,
            max_pages=self.pool.pages_for(self.max_len))
        self._chunks = (PrefillChunks(
            budget=self._prefill_budget,
            max_pages=self.pool.pages_for(self.max_len))
            if self._prefill_budget > 0 else None)
        self._prefilling = []          # worker-owned: mid-prefill reqs
        self._queue = RequestQueue(max_queue_depth)
        self._active = []              # worker-owned slot list
        # static (cohort) mode only: the cohort's row count, pinned at
        # admission — finished rows stay PADDED in the step until the
        # whole cohort drains, the classic static-batching waste the
        # iteration-level scheduler exists to eliminate (and the A/B
        # measures against)
        self._static_rows = 0
        self._reserved = {}            # owner -> worst-case pages
        self._reserved_pages = 0
        self._defer_logged = False
        self.stats = ServingStats(stats_window, engine_id=self.engine_id)
        self.stats.set_queue_depth_fn(lambda: len(self._queue))
        self.decode_stats = DecodeStats(self.engine_id,
                                        window=stats_window)
        self.decode_stats.set_split_fns(lambda: len(self._queue),
                                        lambda: len(self._active))
        self.tenants = tenancy.TenantStats(self.engine_id)
        wfq = tenancy.wfq_depth_gauge()
        for cls in tenancy.TENANT_CLASSES:
            wfq.labels(engine_id=self.engine_id,
                       tenant_class=cls).set_function(
                lambda c=cls: self._queue.depths().get(c, 0))
        self.costs = CostLedger(self.engine_id)
        cc = _REGISTRY.counter(
            "mxnet_tpu_serving_compile_cache_total",
            "per-shape executable cache outcomes at dispatch: "
            "memory_hit (in-process), persistent_hit (on-disk cache "
            "served the compile), miss (fresh backend compile)",
            ("engine_id", "result"))
        self._compile_cache = {
            r: cc.labels(engine_id=self.engine_id, result=r)
            for r in ("memory_hit", "persistent_hit", "miss")}
        self._cc_counts = {r: 0 for r in self._compile_cache}
        self._seen_shapes = set()
        self._shapes_lock = threading.Lock()
        self._compiling_since = None
        # one lock serializes model steps + pool swap: the worker loop,
        # warmup on the caller's thread, and day-one canary traffic
        # must never interleave a step with a cache swap (donated
        # buffers die with the step). A compile legitimately holds it
        # for seconds, hence the long-hold allowance.
        self._forward_lock = threading.Lock()  # mxsan: allow=long-hold
        self._exemplars = exemplar_gate()
        self._slo = None
        # traffic capture (MXNET_TPU_CAPTURE): sampled request corpus
        # behind /capture + deterministic replay — built in start()
        self._capture = None
        self._worker = None
        self._expo = None
        self._wire = None
        self._abort = False
        self._started = False
        self._lock = threading.Lock()
        self._beat = time.monotonic()
        self._last_dispatch = self._beat
        self._probe_name = f"decode_engine_{id(self):x}"
        self._bundle_name = f"decode_scheduler_{self.engine_id}"

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._lock:
            if self._started:
                return self
            if self._queue.closed:
                raise EngineStoppedError("engine cannot be restarted")
            self._started = True
            self._beat = time.monotonic()
            self._last_dispatch = self._beat
            self._worker = threading.Thread(target=self._run,
                                            name="mxnet_tpu_decode",
                                            daemon=True)
            self._worker.start()
        compile_cache.ensure()
        _recorder.install()
        _recorder.register_probe(self._probe_name, self._watchdog_probe)
        # flight bundles carry the decode scheduler's state on any
        # watchdog trip / crash: slot table, queue split, page
        # occupancy — what the on-call needs to see a wedged loop
        _recorder.add_bundle_section(self._bundle_name,
                                     self.scheduler_state)
        _incidents.install()
        _profiling.ensure_started()
        if envvars.get("MXNET_TPU_SLO"):
            from ..telemetry.alerts import (AlertDaemon,
                                            default_burn_rules,
                                            default_decode_objectives,
                                            default_tenant_objectives)
            from ..telemetry.slo import SloEvaluator
            evaluator = SloEvaluator(self.engine_id)
            names = default_decode_objectives(evaluator, self.engine_id)
            names += default_tenant_objectives(evaluator, self.engine_id)
            self._slo = AlertDaemon(evaluator)
            default_burn_rules(self._slo, names)
            self._slo.start()
        # sampled traffic capture: decode records carry the full
        # sampling params + seed, so a corpus replays byte-identically
        # (MXNET_TPU_CAPTURE=0: one env read, nothing built)
        if envvars.get("MXNET_TPU_CAPTURE"):
            from .capture import CaptureStore
            self._capture = CaptureStore(self.engine_id)
        _events.emit("engine_start", engine_id=self.engine_id,
                     decode=True,
                     prefill_buckets=list(self.prefill_bucket_lens),
                     max_rows=self._max_rows,
                     kv_pages=self.pool.n_pages,
                     page_size=self.pool.page_size,
                     iteration_level=self._iteration_level)
        return self

    def stop(self, drain=True, timeout=None):
        """Shut down. ``drain=True`` finishes every queued and
        IN-FLIGHT generation first; ``drain=False`` fails them
        (counted ``cancelled``) — partial token streams end with the
        failure, exactly as ``stream()`` documents."""
        _events.emit("engine_abort" if not drain else "engine_stop",
                     engine_id=self.engine_id, drain=drain)
        _recorder.unregister_probe(self._probe_name)
        _recorder.remove_bundle_section(self._bundle_name)
        if self._slo is not None:
            self._slo.stop()
        if self._capture is not None:
            self._capture.close()
        with self._lock:
            self._queue.close()
            if not drain:
                self._abort = True
            worker = self._worker
        timed_out = False
        if worker is not None:
            worker.join(timeout)
            timed_out = worker.is_alive()
        for r in self._queue.drain_all():
            self.stats.bump("cancelled")
            self.tenants.observe_event(r.tenant, r.tenant_class,
                                       self.model_id, "cancelled")
            r.span.end(error="cancelled: engine stopped")
            r.future.set_exception(
                EngineStoppedError("engine stopped before request ran"))
        self.stats.set_queue_depth_fn(lambda: 0)
        with self._lock:
            expo, self._expo = self._expo, None
            wire, self._wire = self._wire, None
        if wire is not None:
            wire.close()
        if expo is not None:
            expo.close()
        if timed_out:
            raise ServingError("decode worker did not stop in time")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    @property
    def running(self):
        with self._lock:
            return (self._started and self._worker is not None
                    and self._worker.is_alive())

    @property
    def alerts(self):
        return self._slo

    @property
    def capture(self):
        """The engine's :class:`~.capture.CaptureStore` (None unless
        ``MXNET_TPU_CAPTURE`` was on at start)."""
        return self._capture

    def capture_summary(self):
        """The ``/capture`` body (None when capture is disabled) —
        what a fronting router's fleet merge reads per seat."""
        return (self._capture.summary()
                if self._capture is not None else None)

    # -- client surface ----------------------------------------------------
    def submit(self, tokens, token_types=None, deadline_ms=None,
               max_new_tokens=None, eos_id=None, stream=False,
               trace_id=None, parent_span_id=None, temperature=None,
               top_k=None, top_p=None, seed=None, model_id=None,
               tenant=None, tenant_class=None):
        """Enqueue one generation request; returns a STREAMING
        :class:`~.queue.InferenceFuture` — ``result()`` is the full
        (max_new_tokens,) int32 token array, ``stream()`` yields each
        token as it is generated. ``token_types`` is accepted for
        submit-surface compatibility (canaries, generic loadgen) and
        ignored — decode prompts are plain token ids.

        ``temperature``/``top_k``/``top_p``/``seed`` select seeded
        sampling (None = the engine defaults; temperature 0 = greedy).
        Out-of-range values raise
        :class:`~.queue.InvalidSamplingError` here — before any
        compiled step. A sampled request with no seed gets one minted
        at submit, so replay (stream(), failover re-dispatch) draws
        the same tokens.

        ``model_id`` must name THIS engine's model when given (a
        decode engine hosts exactly one — unknown ids are a typed
        404); ``tenant``/``tenant_class`` attribute the request to an
        owner and its WFQ admission class, as on the encoder engine."""
        del token_types
        temperature, top_k, top_p, seed = validate_sampling(
            temperature, top_k, top_p, seed)
        if temperature is None:
            temperature = self._default_temp
        if top_k is None:
            top_k = self._default_top_k
        if top_p is None:
            top_p = self._default_top_p
        if seed is None:
            seed = (int.from_bytes(os.urandom(4), "little") & 0x7FFFFFFF
                    if temperature > 0 else 0)
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms
        if max_new_tokens is None:
            max_new_tokens = self._default_max_new
        if eos_id is None:
            eos_id = self._default_eos
        req = DecodeRequest(tokens, max_new_tokens, eos_id=eos_id,
                            stream=stream, deadline_ms=deadline_ms,
                            trace_id=trace_id,
                            parent_span_id=parent_span_id,
                            temperature=temperature, top_k=top_k,
                            top_p=top_p, seed=seed, tenant=tenant,
                            tenant_class=tenant_class,
                            model_id=model_id)
        req.span.set_attr(engine=self.engine_id, decode=True)
        self.stats.bump("submitted")
        if req.model_id is not None and req.model_id != self.model_id:
            self.stats.bump("rejected_unknown_model")
            self.tenants.observe_event(
                req.tenant, req.tenant_class, str(req.model_id),
                "rejected_unknown_model")
            _events.emit("request_shed", reason="unknown_model",
                         engine_id=self.engine_id,
                         model=str(req.model_id),
                         trace_id=req.trace_id, tokens=req.prompt_len)
            req.span.set_attr(shed="unknown_model").force_keep() \
               .end(error="shed: unknown_model")
            raise UnknownModelError(
                f"unknown model {req.model_id!r}: this decode engine "
                f"hosts {self.model_id!r}")
        req.model_id = self.model_id
        self.tenants.observe_event(req.tenant, req.tenant_class,
                                   req.model_id, "submitted")
        if not self._started or self._queue.closed:
            self.stats.bump("rejected_stopped")
            req.span.end(error="rejected: engine not running")
            raise EngineStoppedError("decode engine is not running")
        too_long = None
        if req.prompt_len > self.prefill_bucket_lens[-1]:
            too_long = (f"prompt of {req.prompt_len} tokens exceeds "
                        f"the largest prefill bucket "
                        f"({self.prefill_bucket_lens[-1]})")
        elif req.prompt_len + req.max_new_tokens > self.max_len:
            too_long = (f"prompt {req.prompt_len} + max_new_tokens "
                        f"{req.max_new_tokens} exceeds the model's "
                        f"max_len ({self.max_len})")
        elif (self.pool.pages_for(req.prompt_len + req.max_new_tokens)
                > self.pool.n_pages):
            too_long = ("request's worst-case KV footprint exceeds "
                        "the whole page pool")
        if too_long is not None:
            self.stats.bump("rejected_too_long")
            self.tenants.observe_event(req.tenant, req.tenant_class,
                                       req.model_id, "rejected_too_long")
            _events.emit("request_shed", reason="too_long",
                         engine_id=self.engine_id,
                         trace_id=req.trace_id, tokens=req.prompt_len)
            req.span.set_attr(shed="too_long").force_keep() \
               .end(error="shed: too_long")
            raise RequestTooLongError(too_long)
        try:
            victim = self._queue.put(req)
        except ServingError as e:
            full = not self._queue.closed
            reason = "queue_full" if full else "stopped"
            self.stats.bump("rejected_queue_full"
                            if full else "rejected_stopped")
            self.tenants.observe_event(
                req.tenant, req.tenant_class, req.model_id,
                "rejected_queue_full" if full else "rejected_stopped")
            _events.emit("request_shed", reason=reason,
                         engine_id=self.engine_id,
                         trace_id=req.trace_id, tokens=req.prompt_len)
            req.span.set_attr(shed=reason).force_keep() \
               .end(error=f"shed: {reason}")
            raise e
        if victim is not None:
            self._shed_victim(victim)
        return req.future

    def _shed_victim(self, victim):
        """Fail a request the WFQ queue evicted to admit a
        higher-class arrival under overload — same contract as the
        encoder engine's shed path."""
        self.stats.bump("rejected_queue_full")
        self.tenants.observe_event(victim.tenant, victim.tenant_class,
                                   victim.model_id or self.model_id,
                                   "shed")
        _events.emit("request_shed", reason="wfq_evicted",
                     engine_id=self.engine_id,
                     trace_id=victim.trace_id,
                     tenant_class=victim.tenant_class,
                     tokens=victim.prompt_len)
        victim.span.set_attr(shed="wfq_evicted").force_keep() \
              .end(error="shed: wfq_evicted")
        victim.future.set_exception(QueueFullError(
            f"shed by weighted-fair admission: queue full and a "
            f"higher class arrived (class {victim.tenant_class})"))

    def infer(self, tokens, max_new_tokens=None, eos_id=None,
              deadline_ms=None, timeout=None, temperature=None,
              top_k=None, top_p=None, seed=None):
        """Synchronous convenience: submit + wait for the full
        generated sequence."""
        return self.submit(tokens, deadline_ms=deadline_ms,
                           max_new_tokens=max_new_tokens,
                           eos_id=eos_id, temperature=temperature,
                           top_k=top_k, top_p=top_p,
                           seed=seed).result(timeout)

    def submit_payload(self, payload):
        """Dispatch-surface adapter (wire listener + HTTP ``/submit``):
        one payload dict in, ``(future, streamed)`` out. The payload's
        decode fields (``max_new_tokens``, ``eos_id``, ``stream``,
        ``temperature``/``top_k``/``top_p``/``seed``) ride the same
        dict the encoder dispatch uses, so old routers that know none
        of them still work."""
        fut = self.submit(payload.get("tokens"),
                          deadline_ms=payload.get("deadline_ms"),
                          max_new_tokens=payload.get("max_new_tokens"),
                          eos_id=payload.get("eos_id"),
                          stream=bool(payload.get("stream")),
                          trace_id=payload.get("trace_id"),
                          parent_span_id=payload.get("span_id"),
                          temperature=payload.get("temperature"),
                          top_k=payload.get("top_k"),
                          top_p=payload.get("top_p"),
                          seed=payload.get("seed"),
                          model_id=payload.get("model_id"),
                          tenant=payload.get("tenant"),
                          tenant_class=payload.get("tenant_class"))
        return fut, bool(payload.get("stream"))

    # -- warmup ------------------------------------------------------------
    def warmup(self, shapes=None, manifest=None):
        """Compile ahead of traffic: every (0, prefill_bucket) prompt
        shape and every (rows, table_width) decode bucket (or the
        given/manifest subset). Dummy forwards write only the pool's
        scratch page. Call BEFORE traffic, like the encoder engine."""
        if manifest is not None:
            if isinstance(manifest, (str, os.PathLike)):
                manifest = compile_cache.load_manifest(manifest)
            universe = set(self._shape_universe())
            want = compile_cache.manifest_shapes(manifest)
            shapes = [s for s in want if s in universe]
            _events.emit("warmup_replay", engine_id=self.engine_id,
                         shapes=len(shapes),
                         skipped_incompatible=len(want) - len(shapes))
        if shapes is None:
            shapes = self._shape_universe()
        for shape in shapes:
            if shape[0] == 0:
                self._forward_prefill_shape(shape[1])
            elif shape[0] < 0:
                self._forward_chunk_shape(-shape[0], shape[1])
            else:
                self._forward_decode_shape(*shape)
        return self

    def _shape_universe(self):
        """Manifest key space: prefill buckets as (0, padded_len),
        decode buckets as (rows, table_width), chunked-prefill buckets
        as (-chunk, table_width) — int pairs, so the fleet manifest
        machinery (union/persist/replay) carries them unchanged and
        encoder engines skip them as incompatible. Dense prefill
        buckets stay in the universe even when chunking is on: the
        static/dense A/B arm and manifest replay both need them."""
        return ([(0, b) for b in self.prefill_bucket_lens]
                + list(self._slots.shape_universe())
                + (list(self._chunks.shape_universe())
                   if self._chunks is not None else []))

    def warmup_manifest(self):
        with self._shapes_lock:
            shapes = sorted(self._seen_shapes)
        return compile_cache.new_manifest(
            self.engine_id, self.prefill_bucket_lens, self._max_rows,
            shapes)

    def reset_stats(self):
        """Fresh measurement window (compile caches + ledger + pool
        untouched) — the bench legs' warmup/measure split."""
        self.stats = ServingStats(self.stats.window,
                                  engine_id=self.engine_id)
        self.stats.set_queue_depth_fn(lambda: len(self._queue))
        self.decode_stats = DecodeStats(self.engine_id,
                                        window=self.decode_stats.window)
        self.decode_stats.set_split_fns(lambda: len(self._queue),
                                        lambda: len(self._active))
        return self

    # -- observability surfaces --------------------------------------------
    def snapshot(self):
        out = self.stats.snapshot()
        out["running"] = self.running
        out["decode"] = self.decode_stats.snapshot()
        out["kv"] = self.pool.occupancy()
        out["kv"]["prefix"] = self.pool.prefix_stats()
        out["prefill_buckets"] = list(self.prefill_bucket_lens)
        out["max_rows"] = self._max_rows
        out["iteration_level"] = self._iteration_level
        out["models"] = {self.model_id: self.model_version}
        out["queue_classes"] = self._queue.depths()
        out["tenants"] = self.tenants.bills()
        out["active_slots"] = len(self._active)
        out["seconds_since_beat"] = round(
            time.monotonic() - self._beat, 3)
        with self._shapes_lock:
            out["compile_cache"] = dict(self._cc_counts)
            out["manifest_shapes"] = len(self._seen_shapes)
        out["compiling"] = self._compiling_since is not None
        out["costs"] = self.costs.totals()
        return out

    def scheduler_state(self):
        """The decode scheduler's live state — the flight-bundle
        section a watchdog trip snapshots, and the `/stats` drill-down
        for a wedged loop."""
        active = [{"trace_id": r.trace_id, "prompt": r.prompt_len,
                   "generated": len(r.generated), "pos": r.pos,
                   "max_new_tokens": r.max_new_tokens,
                   "reused_tokens": r.reused_tokens,
                   "pages": len(self.pool.table(r.id) or ())}
                  for r in list(self._active)]
        prefilling = [{"trace_id": r.trace_id, "prompt": r.prompt_len,
                       "prefill_pos": r.prefill_pos,
                       "reused_tokens": r.reused_tokens}
                      for r in list(self._prefilling)]
        return {"engine_id": self.engine_id,
                "iteration_level": self._iteration_level,
                "prefill_budget": self._prefill_budget,
                "models": {self.model_id: self.model_version},
                "active": active,
                "prefilling": prefilling,
                "prefill_queue_depth": len(self._queue),
                "queue_classes": self._queue.depths(),
                "reserved_pages": self._reserved_pages,
                "kv": self.pool.occupancy(),
                "prefix": self.pool.prefix_stats(),
                "page_refcounts": self.pool.page_refcounts(),
                "decode": self.decode_stats.snapshot()}

    def slo_snapshot(self):
        if self._slo is None:
            return {"owner": self.engine_id, "enabled": False,
                    "objectives": {}}
        return self._slo.evaluator.snapshot()

    def alerts_snapshot(self):
        if self._slo is None:
            return {"owner": self.engine_id, "enabled": False,
                    "rules": []}
        return self._slo.snapshot()

    def cost_table(self):
        """/costs body. Decode iterations land in NEGATED-rows buckets
        (-1, -2, -4, ... — "a decode batch of N rows"; the sign keeps
        them disjoint from prompt-length buckets for any config),
        prefill forwards in their padded prompt-length buckets."""
        return {"engine_id": self.engine_id,
                "buckets": self.costs.table(),
                "totals": self.costs.totals()}

    def whyslow(self):
        """The ``/whyslow`` body: this engine's per-stage attribution
        table + top stages by share of attributed time. Present (with
        ``enabled: false`` and empty tables) even when attribution is
        off, so fleet scrapers never 404-branch."""
        agg = _attribution.get_aggregator(self.engine_id)
        if agg is None:
            return {"owner": self.engine_id,
                    "enabled": _attribution.enabled(),
                    "requests": 0, "stages": [], "top": []}
        return agg.snapshot()

    def expose(self, port=0, host="127.0.0.1"):
        """Telemetry + dispatch surface, mirroring
        ``ServingEngine.expose``; ``POST /submit`` additionally
        understands decode payload fields and — with ``"stream":
        true`` — answers with chunked JSON lines, one per generated
        token, final body last (the HTTP fallback for wire-less
        peers). The binary wire listener streams partial RESULT
        frames for the same requests (``MXNET_TPU_WIRE=0`` opts out)."""
        from ..telemetry.expo import TelemetryServer

        with self._lock:
            if self._queue.closed:
                raise EngineStoppedError(
                    "cannot expose telemetry on a stopped engine")
            if self._expo is not None:
                return self._expo

            def healthz():
                alive = (self._worker is not None
                         and self._worker.is_alive())
                closed = self._queue.closed
                wire = self._wire
                return (alive and not closed,
                        {"engine_id": self.engine_id, "decode": True,
                         "models": {self.model_id: self.model_version},
                         "worker_alive": alive, "queue_closed": closed,
                         "queue_depth": len(self._queue),
                         "active_slots": len(self._active),
                         "kv_occupancy":
                             self.pool.occupancy()["occupancy"],
                         "compiling": self._compiling_since is not None,
                         "wire_port": (wire.port if wire is not None
                                       else None),
                         "seconds_since_beat":
                             round(time.monotonic() - self._beat, 3)})

            srv = TelemetryServer(healthz_fn=healthz,
                                  stats_fn=self.snapshot,
                                  submit_fn=self._remote_submit,
                                  warmup_fn=self.warmup_manifest,
                                  costs_fn=self.cost_table,
                                  slo_fn=(self.slo_snapshot
                                          if self._slo is not None
                                          else None),
                                  alerts_fn=(self.alerts_snapshot
                                             if self._slo is not None
                                             else None),
                                  whyslow_fn=self.whyslow,
                                  capture_fn=(self._capture.summary
                                              if self._capture is not None
                                              else None),
                                  port=port, host=host)
            self._expo = srv
            if envvars.get("MXNET_TPU_WIRE") and self._wire is None:
                from .wire import WireListener
                try:
                    self._wire = WireListener(self, host=host)
                except OSError as e:
                    _events.emit("wire_listen_error",
                                 engine_id=self.engine_id,
                                 error=repr(e))
        _events.emit("telemetry_expose", engine_id=self.engine_id,
                     port=srv.port, host=srv.host)
        return srv

    def _remote_submit(self, payload):
        """``POST /submit`` handler. Non-streamed: block, one JSON
        body (the encoder contract, token array as the result).
        Streamed (``"stream": true``): returns a part GENERATOR the
        exposition server writes as chunked JSON lines — partial
        tokens flow while the model generates, the final line carries
        the authoritative full sequence."""
        t0 = time.perf_counter()
        try:
            fut, streamed = self.submit_payload(payload)
        except (ServingError, ValueError, LookupError, TypeError) as e:
            name = type(e).__name__
            return (_SUBMIT_ERROR_STATUS.get(name, 400),
                    {"ok": False, "error_type": name, "error": str(e),
                     "engine_id": self.engine_id})
        timeout_s = float(payload.get("timeout_s") or 600.0)
        if not streamed:
            try:
                out = fut.result(timeout=timeout_s)
            except Exception as e:
                name = type(e).__name__
                return (_SUBMIT_ERROR_STATUS.get(name, 500),
                        {"ok": False, "error_type": name,
                         "error": str(e), "trace_id": fut.trace_id,
                         "engine_id": self.engine_id})
            # "decode": True marks the result as TOKEN IDS so an
            # HTTP-fallback router restores int32 even when the
            # request itself carried no decode params (engine-default
            # max_new_tokens)
            return 200, {"ok": True, "result": np.asarray(out).tolist(),
                         "decode": True,
                         "trace_id": fut.trace_id,
                         "engine_id": self.engine_id,
                         "engine_ms": round(
                             (time.perf_counter() - t0) * 1e3, 3),
                         "cost": getattr(fut, "cost", None),
                         "breakdown": getattr(fut, "breakdown", None)}

        def parts():
            n = 0
            try:
                for part in fut.stream(timeout=timeout_s):
                    yield {"seq": n, "token": int(part["token"]),
                           "final": False, "trace_id": fut.trace_id}
                    n += 1
                out = fut.result(timeout=0)
            except Exception as e:
                yield {"ok": False, "final": True,
                       "error_type": type(e).__name__, "error": str(e),
                       "trace_id": fut.trace_id,
                       "engine_id": self.engine_id}
                return
            yield {"ok": True, "final": True, "seq": n,
                   "result": np.asarray(out).tolist(),
                   "trace_id": fut.trace_id,
                   "engine_id": self.engine_id,
                   "engine_ms": round(
                       (time.perf_counter() - t0) * 1e3, 3),
                   "cost": getattr(fut, "cost", None),
                   "breakdown": getattr(fut, "breakdown", None)}

        return 200, parts()

    # -- watchdog ----------------------------------------------------------
    def _watchdog_probe(self):
        if not self.running:
            return None
        now = time.monotonic()
        stall = _recorder.stall_seconds()
        if self._compiling_since is not None:
            stall += envvars.get("MXNET_TPU_WATCHDOG_COMPILE_GRACE_S")
        since_beat = now - self._beat
        if since_beat > stall:
            return {"kind": "decode_worker_stall",
                    "seconds_since_beat": round(since_beat, 3),
                    "active_slots": len(self._active),
                    "queue_depth": len(self._queue)}
        depth = len(self._queue)
        if (depth >= self._queue.max_depth
                and now - self._last_dispatch > stall):
            return {"kind": "decode_queue_saturated",
                    "queue_depth": depth,
                    "seconds_since_dispatch": round(
                        now - self._last_dispatch, 3)}
        return None

    # -- compile tracking --------------------------------------------------
    def _bump_cc(self, result):
        with self._shapes_lock:
            self._cc_counts[result] += 1
        self._compile_cache[result].inc()

    def _step_compiled(self, shape_key, fn):
        """Run one model step, classifying the executable-cache
        outcome for ``shape_key`` exactly as the encoder engine does
        (memory_hit / persistent_hit / miss, compile-grace window for
        the watchdog). Returns (result, wall_s, first_visit)."""
        with self._shapes_lock:
            hit = shape_key in self._seen_shapes
        if hit:
            self._bump_cc("memory_hit")
            t0 = time.perf_counter()
            out = fn()
            dt = time.perf_counter() - t0
            return out, dt, False
        _events.emit("compile_begin", engine_id=self.engine_id,
                     shape=list(shape_key))
        cc_before = compile_cache.events_snapshot()
        self._compiling_since = time.monotonic()
        t0 = time.perf_counter()
        try:
            out = fn()
        finally:
            self._beat = time.monotonic()
            self._compiling_since = None
        dt = time.perf_counter() - t0
        result = compile_cache.classify(cc_before,
                                        compile_cache.events_snapshot())
        self._bump_cc(result)
        with self._shapes_lock:
            self._seen_shapes.add(shape_key)
        self.stats.bump("compiles")
        self.stats.compile_ms.observe(dt * 1e3)
        _events.emit("compile_end", engine_id=self.engine_id,
                     shape=list(shape_key), result=result,
                     ms=round(dt * 1e3, 3))
        return out, dt, True

    # -- warmup forwards ---------------------------------------------------
    def _forward_prefill_shape(self, bucket):
        ids = np.zeros(bucket, np.int32)
        phys = np.full(bucket, self.pool.scratch_page, np.int32)
        off = (np.arange(bucket) % self.pool.page_size).astype(np.int32)

        def run():
            with self._forward_lock:
                tok, caches = self._model.prefill(
                    self.pool.caches, ids, bucket, phys, off)
                self.pool.swap(caches)
            return tok

        _out, dt, compiled = self._step_compiled((0, bucket), run)
        self.costs.observe_warmup(bucket, dt, compiled=compiled)

    def _forward_chunk_shape(self, chunk, width):
        ids = np.zeros(chunk, np.int32)
        table = np.full(width, self.pool.scratch_page, np.int32)

        def run():
            with self._forward_lock:
                tok, caches = self._model.prefill_chunk(
                    self.pool.caches, ids, 0, chunk, table)
                self.pool.swap(caches)
            return tok

        _out, dt, compiled = self._step_compiled((-chunk, width), run)
        # chunk warmups bill into the positive token-count bucket —
        # they may merge with a same-sized dense prefill bucket, which
        # is fine: both are "prompt tokens prefilled" entries
        self.costs.observe_warmup(chunk, dt, compiled=compiled)

    def _forward_decode_shape(self, rows, width):
        ids = np.zeros(rows, np.int32)
        positions = np.zeros(rows, np.int32)
        tables = np.full((rows, width), self.pool.scratch_page,
                         np.int32)

        def run():
            with self._forward_lock:
                toks, caches = self._model.decode_step(
                    self.pool.caches, ids, positions, tables)
                self.pool.swap(caches)
            return toks

        _out, dt, compiled = self._step_compiled((rows, width), run)
        self.costs.observe_warmup(-rows, dt, compiled=compiled)

    # -- worker ------------------------------------------------------------
    def _run(self):
        while True:
            self._beat = time.monotonic()
            if self._abort:
                self._fail_all(EngineStoppedError(
                    "engine stopped before generation finished"))
                return
            self._admit()
            self._advance_prefills()
            if not self._active:
                if (self._queue.closed and not len(self._queue)
                        and not self._prefilling):
                    return
                continue
            try:
                self._iterate()
            except Exception as e:
                # a poison iteration fails ITS batch, never the engine:
                # every active sequence is failed (their streams end
                # with the error) and their pages recycle; queued
                # requests get a fresh batch next loop
                for req in self._active:
                    self._leave(req, error=e)
                self._active = []

    def _fail_all(self, exc):
        for req in self._active:
            self._leave(req, error=exc, counter="cancelled")
        self._active = []
        for req in self._prefilling:
            self._leave(req, error=exc, counter="cancelled",
                        joined=False)
        self._prefilling = []
        for req in self._queue.drain_all():
            self.stats.bump("cancelled")
            self.tenants.observe_event(req.tenant, req.tenant_class,
                                       self.model_id, "cancelled")
            req.span.end(error="cancelled: engine stopped")
            req.future.set_exception(exc)

    def _admit(self):
        """Join queued prompts at this iteration boundary. Chunked
        mode moves them into the PREFILLING set (pages reserved,
        prefix index consulted) for the chunk scheduler to advance;
        dense mode runs the whole prefill here. Static mode
        (``iteration_level=False``) admits only into an EMPTY batch
        and pins the cohort's row count until it fully drains — the
        classic cohort scheduler the A/B leg measures against."""
        if not self._iteration_level and self._active:
            return
        if not self._active and not self._prefilling:
            self._static_rows = 0
        chunked = self._chunks is not None
        admitted = 0
        while True:
            live = len(self._active) + len(self._prefilling)
            if live >= self._max_rows:
                break
            if chunked:
                # cap CONCURRENT chunked prefills — more would just
                # time-slice the same per-iteration token budget
                if len(self._prefilling) >= self._prefills_per_iter:
                    break
            elif admitted >= (self._prefills_per_iter if self._active
                              else self._max_rows):
                break
            # idle engines park on the queue poll; a running batch
            # polls without waiting (the decode loop must not linger)
            idle = not self._active and not self._prefilling \
                and not admitted
            reqs = self._queue.poll(1, timeout=0.05 if idle else 0.0)
            if not reqs:
                break
            req = reqs[0]
            now = time.monotonic()
            if req.expired(now):
                self.stats.bump("expired")
                self.tenants.observe_event(req.tenant, req.tenant_class,
                                           self.model_id, "expired")
                _events.emit("request_expired", trace_id=req.trace_id,
                             waited_ms=round(
                                 (now - req.t_submit) * 1e3, 3))
                req.span.end(error="deadline exceeded before prefill")
                req.future.set_exception(DeadlineExceededError(
                    f"request {req.id} deadline exceeded before "
                    "prefill"))
                continue
            worst = self.pool.pages_for(req.prompt_len
                                        + req.max_new_tokens)
            if self._reserved_pages + worst > self.pool.n_pages:
                # the pool cannot GUARANTEE this sequence's worst case:
                # defer (front of line), never fail — pages recycle the
                # moment any sequence leaves
                self._queue.requeue(req)
                # per-REQUEST defer breadcrumbs: the episode gets its
                # own stage span once the re-admit finally lands, so a
                # deferred request's TTFT outlier reads "defer", not
                # noise (the event below stays once-per-pool-episode —
                # the admit loop would re-emit it every poll otherwise)
                if req.t_defer is None:
                    req.t_defer = now
                req.defers += 1
                if not self._defer_logged:
                    self._defer_logged = True
                    _events.emit("decode_defer",
                                 engine_id=self.engine_id,
                                 trace_id=req.trace_id,
                                 need_pages=worst,
                                 reserved=self._reserved_pages,
                                 pool=self.pool.n_pages)
                break
            if req.t_defer is not None:
                # the defer episode just ended: admission is about to
                # succeed (or fail loudly) — stamp requeue -> now
                _events.emit("decode_defer_end",
                             engine_id=self.engine_id,
                             trace_id=req.trace_id,
                             deferrals=req.defers,
                             waited_ms=round(
                                 (now - req.t_defer) * 1e3, 3))
                _attribution.stamp(req, "defer", req.t_defer, now,
                                   attrs={"deferrals": req.defers})
                req.t_defer = None
            try:
                if chunked:
                    self._admit_chunked(req, worst)
                else:
                    self._prefill(req, worst)
            except Exception as e:
                self.pool.release(req.id)
                self._unreserve(req)
                self.stats.bump("failed")
                req.span.end(error=repr(e))
                req.future.set_exception(e)
                continue
            admitted += 1

    def _admit_chunked(self, req, worst_pages):
        """Reserve the worst case, consult the prefix index, and hand
        the request to the chunk scheduler. A prefix hit attaches the
        matched read-only pages to the request's table (COW copies
        materialized before anything reads them) and fast-forwards
        ``prefill_pos`` past the reused tokens — those positions'
        K/V are already in the pool."""
        self._reserved[req.id] = worst_pages
        self._reserved_pages += worst_pages
        matched, copies = self.pool.match_prefix(req.id, req.tokens)
        if copies:
            c0 = time.monotonic()
            with self._forward_lock:
                self.pool.copy_pages(copies)
            _attribution.stamp(req, "cow_copy", c0, time.monotonic(),
                               attrs={"pages": len(copies),
                                      "prefix_hit": True})
        req.prefill_pos = req.reused_tokens = matched
        self.stats.queue_ms.observe((req.t_drain - req.t_submit) * 1e3)
        self._prefilling.append(req)
        if matched:
            _events.emit("decode_prefix_hit", engine_id=self.engine_id,
                         trace_id=req.trace_id, matched=matched,
                         prompt=req.prompt_len, cow_pages=len(copies))

    def _advance_prefills(self):
        """Spend this iteration boundary's prefill-token budget
        (``MXNET_TPU_DECODE_PREFILL_BUDGET``) advancing mid-prefill
        prompts, FIFO — the running decode batch waits for at most
        one budget's worth of chunk steps, however long the prompts
        are. A prompt whose last chunk lands emits its first token
        and joins the decode batch."""
        if self._chunks is None or not self._prefilling:
            return
        budget = self._prefill_budget
        done = []
        for req in self._prefilling:
            if budget <= 0:
                break
            if req.expired():
                done.append(req)
                self.stats.bump("expired")
                _events.emit("request_expired", trace_id=req.trace_id,
                             waited_ms=round(
                                 (time.monotonic() - req.t_submit)
                                 * 1e3, 3))
                self._leave(req, error=DeadlineExceededError(
                    f"request {req.id} deadline exceeded during "
                    "chunked prefill"), counter="expired", joined=False)
                continue
            try:
                tok = None
                while budget > 0 and req.prefill_pos < req.prompt_len:
                    take = min(budget,
                               req.prompt_len - req.prefill_pos)
                    tok = self._prefill_chunk(req, take)
                    budget -= take
                if req.prefill_pos >= req.prompt_len:
                    done.append(req)
                    self._finish_prefill(req, tok)
            except Exception as e:
                if req not in done:
                    done.append(req)
                self._active = [r for r in self._active
                                if r.id != req.id]
                self.stats.bump("failed")
                self._leave(req, error=e, joined=False)
        if done:
            left = {r.id for r in done}
            self._prefilling = [r for r in self._prefilling
                                if r.id not in left]

    def _prefill_chunk(self, req, take):
        """One kernel-sized prompt slice through the paged chunk step.
        Returns the step's next-token sample — meaningful only for
        the chunk that completes the prompt (sampled at the prompt's
        last position); earlier chunks' is discarded."""
        t_chunk0 = time.monotonic()
        start = req.prefill_pos
        self.pool.ensure(req.id, start + take)
        pages_now = self.pool.pages_for(start + take)
        neg_chunk, width = self._chunks.bucket(take, pages_now)
        chunk = -neg_chunk
        ids = np.zeros(chunk, np.int32)
        ids[:take] = req.tokens[start:start + take]
        # the chunk's first write page could be a shared page at this
        # sequence's write frontier (a prefix hit whose match ended
        # exactly on a page boundary that is still index-pinned from
        # another chain) — copy-on-write before writing into it
        pairs = []
        cow = self.pool.prepare_write(req.id, start)
        if cow is not None:
            pairs.append(cow)
        table = self.pool.padded_tables([req.id], width)[0]

        cow_ival = [None]

        def run():
            with self._forward_lock:
                if pairs:
                    c0 = time.monotonic()
                    self.pool.copy_pages(pairs)
                    cow_ival[0] = (c0, time.monotonic())
                tok, caches = self._model.prefill_chunk(
                    self.pool.caches, ids, start, take, table,
                    temperature=req.temperature, top_k=req.top_k,
                    top_p=req.top_p, seed=req.seed)
                self.pool.swap(caches)
            return int(tok)

        tok, dt, compiled = self._step_compiled((neg_chunk, width), run)
        now = time.monotonic()
        self._beat = now
        self._last_dispatch = now
        # stage stamps: the chunk's full residency, with the COW copy
        # nested inside it (the extractor bills the copy slice to
        # cow_copy, the remainder to prefill_chunk — innermost wins)
        _attribution.stamp(req, "prefill_chunk", t_chunk0, now,
                           attrs={"tokens": take, "pos": start,
                                  "compiled": compiled})
        if cow_ival[0] is not None:
            _attribution.stamp_interval(req, "cow_copy", cow_ival[0],
                                        attrs={"pages": len(pairs)})
        req.prefill_pos += take
        req.device_s += dt
        final = req.prefill_pos >= req.prompt_len
        done_now = final and (
            req.max_new_tokens == 1
            or (req.eos_id is not None and tok == req.eos_id))
        self.decode_stats.observe_chunk(take)
        # chunk steps bill by their REAL token count (the final chunk
        # adds the first generated token), so per-request bills —
        # (prompt - reused) + generated — reconcile against the
        # ledger token-for-token, exactly as the dense path does
        self.costs.observe_decode(chunk, dt, tokens=take + int(final),
                                  completed=int(done_now),
                                  compiled=compiled)
        return tok

    def _finish_prefill(self, req, tok):
        """The prompt's last chunk just ran: index its full pages for
        future prefix hits, emit the first generated token, and join
        the decode batch (or finish outright on EOS / a 1-token
        cap)."""
        self.pool.register_prefix(req.id, req.tokens)
        now = time.monotonic()
        req.t_first = req.t_last = now
        self.decode_stats.ttft_ms.observe((now - req.t_submit) * 1e3)
        self._emit_token(req, tok)
        if self._done_after_token(req, tok):
            self._leave(req, reason=self._leave_reason(req, tok),
                        joined=False)
            return
        self._active.append(req)
        self.decode_stats.observe_join()
        _events.emit("decode_join", engine_id=self.engine_id,
                     trace_id=req.trace_id, prompt=req.prompt_len,
                     reused_tokens=req.reused_tokens,
                     max_new_tokens=req.max_new_tokens,
                     active=len(self._active))

    def _unreserve(self, req):
        worst = self._reserved.pop(req.id, 0)
        self._reserved_pages -= worst

    def _prefill(self, req, worst_pages):
        """Run one prompt through the DENSE prefill step (static mode
        and the chunked-prefill A/B baseline), emit the first token,
        and either finish the request (max_new_tokens=1 / EOS on token
        one) or JOIN it to the decode batch."""
        self._reserved[req.id] = worst_pages
        self._reserved_pages += worst_pages
        t_pf0 = time.monotonic()
        bucket = next(b for b in self.prefill_bucket_lens
                      if b >= req.prompt_len)
        self.pool.ensure(req.id, req.prompt_len)
        req.prefill_pos = req.prompt_len
        ids = np.zeros(bucket, np.int32)
        ids[:req.prompt_len] = req.tokens
        phys, off = self.pool.scatter_indices(req.id, req.prompt_len,
                                              bucket)

        def run():
            with self._forward_lock:
                tok, caches = self._model.prefill(
                    self.pool.caches, ids, req.prompt_len, phys, off,
                    temperature=req.temperature, top_k=req.top_k,
                    top_p=req.top_p, seed=req.seed)
                self.pool.swap(caches)
            return int(tok)

        tok, dt, compiled = self._step_compiled((0, bucket), run)
        # prefill always carries exactly one live request, so its wall
        # lands in request_s (observe_decode) — what keeps
        # sum(per-request bills) == ledger request_s exact; the
        # request is counted once, at leave — which IS now for a
        # generation that ends on its first token (max_new_tokens=1,
        # or EOS immediately). Tokens: the prompt PLUS the first
        # generated token, matching the bills' unit token-for-token.
        done_now = (req.max_new_tokens == 1
                    or (req.eos_id is not None and tok == req.eos_id))
        self.costs.observe_decode(bucket, dt,
                                  tokens=req.prompt_len + 1,
                                  completed=int(done_now),
                                  compiled=compiled)
        now = time.monotonic()
        self._last_dispatch = now
        req.t_first = req.t_last = now
        req.device_s += dt
        _attribution.stamp(req, "prefill", t_pf0, now,
                           attrs={"tokens": req.prompt_len,
                                  "compiled": compiled})
        self.decode_stats.ttft_ms.observe((now - req.t_submit) * 1e3)
        self.stats.queue_ms.observe((req.t_drain - req.t_submit) * 1e3)
        self._emit_token(req, tok)
        if self._done_after_token(req, tok):
            self._leave(req, reason=self._leave_reason(req, tok),
                        joined=False)
            return
        self._active.append(req)
        self.decode_stats.observe_join()
        _events.emit("decode_join", engine_id=self.engine_id,
                     trace_id=req.trace_id, prompt=req.prompt_len,
                     max_new_tokens=req.max_new_tokens,
                     active=len(self._active))

    def _emit_token(self, req, tok):
        req.generated.append(tok)
        self.decode_stats.observe_token()
        req.future.push_part({"index": len(req.generated) - 1,
                              "token": tok, "final": False})

    @staticmethod
    def _done_after_token(req, tok):
        return (len(req.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id))

    @staticmethod
    def _leave_reason(req, tok):
        if req.eos_id is not None and tok == req.eos_id:
            return "eos"
        return "max_tokens"

    def _iterate(self):
        """One decode iteration: every live sequence advances one
        token through the bucketed paged step; EOS/max-token leavers
        recycle their pages the same iteration."""
        active = self._active
        t_iter0 = time.monotonic()
        cow_pairs = []
        cow_reqs = []
        for req in active:
            # guaranteed by the admission reservation: never raises
            self.pool.ensure(req.id, req.pos + 1)
            # a shared prefix page at this row's write frontier gets a
            # private copy before the step writes into it (no-op for
            # private pages — one set lookup)
            cow = self.pool.prepare_write(req.id, req.pos)
            if cow is not None:
                cow_pairs.append(cow)
                cow_reqs.append(req)
        # ensure() just covered pos+1 for every row, so the page count
        # is pure arithmetic — no pool lock or table copy per token
        max_pages = max(self.pool.pages_for(req.pos + 1)
                        for req in active)
        n_rows = len(active)
        if not self._iteration_level:
            # classic static batching: the cohort's row count is
            # pinned at admission; rows whose sequences finished keep
            # burning padded slots until the LAST member drains
            self._static_rows = max(self._static_rows, n_rows)
            n_rows = self._static_rows
        rows_b, width_b = self._slots.bucket(n_rows, max_pages)
        ids = np.zeros(rows_b, np.int32)
        positions = np.zeros(rows_b, np.int32)
        temps = np.zeros(rows_b, np.float32)
        top_ks = np.zeros(rows_b, np.int32)
        top_ps = np.ones(rows_b, np.float32)
        seeds = np.zeros(rows_b, np.int32)
        for i, req in enumerate(active):
            ids[i] = req.generated[-1]
            positions[i] = req.pos
            temps[i] = req.temperature
            top_ks[i] = req.top_k
            top_ps[i] = req.top_p
            seeds[i] = req.seed
        owners = [req.id for req in active] \
            + ["__pad__"] * (rows_b - len(active))
        tables = self.pool.padded_tables(owners, width_b)

        cow_ival = [None]

        def run():
            with self._forward_lock:
                if cow_pairs:
                    c0 = time.monotonic()
                    self.pool.copy_pages(cow_pairs)
                    cow_ival[0] = (c0, time.monotonic())
                toks, caches = self._model.decode_step(
                    self.pool.caches, ids, positions, tables,
                    temperatures=temps, top_ks=top_ks, top_ps=top_ps,
                    seeds=seeds)
                toks = np.asarray(toks)
                self.pool.swap(caches)
            return toks

        toks, dt, compiled = self._step_compiled((rows_b, width_b), run)
        now = time.monotonic()
        self._beat = now
        self._last_dispatch = now
        n_active = len(active)
        leavers = []
        share = dt / n_active
        completed = 0
        for i, req in enumerate(active):
            tok = int(toks[i])
            self.decode_stats.inter_token_ms.observe(
                (now - req.t_last) * 1e3)
            req.t_last = now
            req.pos += 1
            req.device_s += share
            # iteration residency: every cohort member was resident
            # for the whole step; a member whose row paid a COW copy
            # gets the copy slice re-billed to cow_copy (nested stamp)
            _attribution.stamp(req, "decode_iter", t_iter0, now)
            self._emit_token(req, tok)
            if self._done_after_token(req, tok):
                leavers.append((req, self._leave_reason(req, tok)))
                completed += 1
        if cow_ival[0] is not None:
            for req in cow_reqs:
                _attribution.stamp_interval(req, "cow_copy",
                                            cow_ival[0])
        self.decode_stats.observe_iteration(rows_b, n_active)
        self.stats.compute_ms.observe(dt * 1e3)
        self.costs.observe_decode(-rows_b, dt, tokens=n_active,
                                  completed=completed,
                                  compiled=compiled)
        if leavers:
            left = {req.id for req, _ in leavers}
            self._active = [r for r in active if r.id not in left]
            for req, reason in leavers:
                self._leave(req, reason=reason)

    def _leave(self, req, reason=None, error=None, counter="failed",
               joined=True):
        """Retire one sequence: pages recycled immediately, stream
        closed with the final result (or the failure)."""
        freed = self.pool.release(req.id)
        self._unreserve(req)
        self._defer_logged = False
        if joined:
            self.decode_stats.observe_leave()
        if error is not None:
            self.stats.bump(counter)
            self.tenants.observe_event(req.tenant, req.tenant_class,
                                       self.model_id, counter)
            req.span.end(error=repr(error))
            if self._capture is not None:
                self._capture.record_request(
                    req, None, counter,
                    (time.monotonic() - req.t_submit) * 1e3,
                    model=self.model_id, version=self.model_version,
                    engine_id=self.engine_id)
            req.future.set_exception(error)
            return
        now = time.monotonic()
        req.t_done = now
        out = np.asarray(req.generated, np.int32)
        total_ms = (now - req.t_submit) * 1e3
        self.stats.total_ms.observe(
            total_ms, exemplar=slow_exemplar(req.trace_id, total_ms,
                                             self._exemplars))
        self.stats.bump("completed")
        self.tenants.observe_event(req.tenant, req.tenant_class,
                                   self.model_id, "completed")
        self.tenants.observe_latency(req.tenant, req.tenant_class,
                                     self.model_id, total_ms)
        self.tenants.observe_cost(
            req.tenant, req.tenant_class, self.model_id, req.device_s,
            req.prompt_len - req.reused_tokens + len(req.generated))
        # "tokens" mirrors the ledger's accounting unit (prompt tokens
        # PREFILLED — prefix-reused ones never hit the device — plus
        # tokens generated) so client-summed bills reconcile against
        # the /costs delta token-for-token
        req.future.cost = {"engine_id": self.engine_id,
                           "bucket": "decode",
                           "model": self.model_id,
                           "tenant": req.tenant,
                           "tenant_class": req.tenant_class,
                           "device_s": req.device_s,
                           "compiled": False,
                           "tokens": (req.prompt_len - req.reused_tokens
                                      + len(req.generated)),
                           "generated_tokens": len(req.generated),
                           "prompt_tokens": req.prompt_len,
                           "reused_tokens": req.reused_tokens,
                           "batch_requests": 1}
        _events.emit("decode_leave", engine_id=self.engine_id,
                     trace_id=req.trace_id, reason=reason,
                     tokens=len(req.generated), pages_freed=freed,
                     active=len(self._active))
        # critical-path decomposition: the engine-measured numbers the
        # router and loadgen will see verbatim (future.breakdown, the
        # streamed-final RESULT frame) + the /whyslow fleet aggregate
        if req.stages is not None:
            breakdown = _attribution.breakdown_from_stamps(
                req.stages, req.t_submit, now, trace_id=req.trace_id)
            req.future.breakdown = breakdown
            _attribution.aggregator(self.engine_id).observe(
                breakdown, tenant_class=req.tenant_class,
                model=self.model_id, trace_id=req.trace_id)
        req.span.set_attr(tokens=len(req.generated), reason=reason)
        req.span.end()
        # capture after breakdown/cost landed (the record carries
        # both) and before the result fires — a caller observing
        # completion finds its record already durable
        if self._capture is not None:
            self._capture.record_request(
                req, out, "completed", total_ms, model=self.model_id,
                version=self.model_version, engine_id=self.engine_id)
        req.future.set_result(out)
