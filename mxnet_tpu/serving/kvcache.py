"""Paged KV cache: the decode engine's attention memory.

The vLLM idea, sized for this serving engine: instead of one
contiguous (max_len) KV buffer per sequence — whose worst-case
reservation wastes most of the pool on short chats — the cache is a
POOL of fixed-size pages (``MXNET_TPU_KV_PAGE_SIZE`` tokens each,
``MXNET_TPU_KV_PAGES`` total), preallocated once per layer as
``(P, H, page_size, D)`` device arrays. Each live sequence owns a
PAGE TABLE (an ordered list of physical page ids); growing past a
page boundary allocates exactly one more page, and a finished
sequence returns its pages to the free list the same iteration it
leaves the batch — memory fragmentation is impossible by construction
(every page is the same size) and occupancy is a first-class metric.

Isolation is per-page OWNER ATTRIBUTION: a page belongs to exactly
one sequence for its whole allocation (pages are never shared), the
pool records the owner, and :meth:`PagedKVPool.check_isolated`
asserts the invariant (disjoint tables, free pages unowned) — the
decode analog of the packed encoder path's segment ids. The decode
kernel (``ops.pallas.flash_attention.paged_flash_attention``) then
reads K/V through the table with per-row ``kv_len`` masking, so one
sequence can never attend into another's pages even though they share
the physical pool.

The pool's arrays flow THROUGH the jitted decode/prefill steps as
donated buffers (``jax.jit(..., donate_argnums=...)``): the step
consumes the old cache arrays and returns the updated ones, XLA
reuses the storage, and steady-state decode performs no per-step
cache-sized allocation (the resource-watermark assertion in
tests/test_decode.py pins this).
"""
from __future__ import annotations

import threading

import numpy as np

from .. import envvars
from ..telemetry.registry import REGISTRY
from .queue import ServingError

__all__ = ["KVPagesExhaustedError", "PagedKVPool"]


class KVPagesExhaustedError(ServingError):
    """The page pool cannot hold another page: backpressure for the
    decode admission path (the engine defers the join — the request
    waits in the prefill queue until pages recycle)."""


def _kv_pages_gauge(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.gauge(
        "mxnet_tpu_serving_kv_pages",
        "paged KV cache pool pages by state (used/free), per engine",
        ("engine_id", "state"))


def _kv_events_counter(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.counter(
        "mxnet_tpu_serving_kv_page_events_total",
        "paged KV cache pool events: alloc/free (pages) and exhausted "
        "(refused allocations), per engine", ("engine_id", "event"))


class PagedKVPool:
    """Fixed-size-page KV pool with per-sequence page tables.

    Parameters
    ----------
    n_layers / n_heads / head_dim : the model's KV geometry — one
        (K, V) page array pair per layer, shaped
        ``(n_pages, n_heads, page_size, head_dim)``.
    page_size : tokens per page (default ``MXNET_TPU_KV_PAGE_SIZE``).
    n_pages : pool capacity (default ``MXNET_TPU_KV_PAGES``).
    dtype : cache dtype (the model's activation dtype).
    engine_id : label for the pool's metric families.

    ``caches`` is a flat tuple ``(k0, v0, k1, v1, ...)`` — the pytree
    the jitted decode step takes as its DONATED first argument and
    returns updated; the engine writes the returned tuple back with
    :meth:`swap`. All bookkeeping (free list, tables, owners) is
    host-side and thread-safe; array contents are only ever touched
    inside the jitted steps.
    """

    def __init__(self, n_layers, n_heads, head_dim, page_size=None,
                 n_pages=None, dtype="float32", engine_id="default",
                 registry=None):
        import jax.numpy as jnp

        self.page_size = int(page_size if page_size is not None
                             else envvars.get("MXNET_TPU_KV_PAGE_SIZE"))
        self.n_pages = int(n_pages if n_pages is not None
                           else envvars.get("MXNET_TPU_KV_PAGES"))
        if self.page_size < 1 or self.n_pages < 1:
            raise ValueError(
                f"bad page pool geometry: {self.n_pages} pages of "
                f"{self.page_size} tokens")
        self.n_layers = int(n_layers)
        self.engine_id = str(engine_id)
        # one extra SCRATCH page (id n_pages, never allocated): padded
        # decode-batch rows and prefill tail padding write there, so a
        # dummy row can never clobber a live sequence's page
        self.scratch_page = self.n_pages
        shape = (self.n_pages + 1, int(n_heads), self.page_size,
                 int(head_dim))
        self.caches = tuple(
            jnp.zeros(shape, dtype=jnp.dtype(dtype))
            for _ in range(2 * self.n_layers))
        self._lock = threading.Lock()
        # LIFO free list: a just-freed (cache-warm) page is reused first
        self._free = list(range(self.n_pages - 1, -1, -1))
        self._tables = {}               # owner -> [page ids] in order
        # per-page attribution (+1: the scratch page, never owned)
        self._owner = [None] * (self.n_pages + 1)
        ev = _kv_events_counter(registry)
        self._c_alloc = ev.labels(engine_id=self.engine_id, event="alloc")
        self._c_free = ev.labels(engine_id=self.engine_id, event="free")
        self._c_exhausted = ev.labels(engine_id=self.engine_id,
                                      event="exhausted")
        g = _kv_pages_gauge(registry)
        # pull gauges: scrape-time reads, zero hot-path cost
        g.labels(engine_id=self.engine_id, state="used") \
            .set_function(lambda: self.n_pages - len(self._free))
        g.labels(engine_id=self.engine_id, state="free") \
            .set_function(lambda: len(self._free))

    # -- geometry ----------------------------------------------------------
    def pages_for(self, kv_len):
        """Pages needed to hold ``kv_len`` tokens."""
        return -(-int(kv_len) // self.page_size)

    @property
    def bytes_total(self):
        return sum(int(np.prod(c.shape)) * c.dtype.itemsize
                   for c in self.caches)

    # -- allocation --------------------------------------------------------
    def ensure(self, owner, kv_len):
        """Grow ``owner``'s table to hold ``kv_len`` tokens; returns
        the table. Atomic: either every page needed is allocated or
        none is (:class:`KVPagesExhaustedError`) — a half-grown
        sequence could never run its next step."""
        need_pages = self.pages_for(kv_len)
        with self._lock:
            table = self._tables.setdefault(owner, [])
            grow = need_pages - len(table)
            if grow <= 0:
                return list(table)
            if grow > len(self._free):
                self._c_exhausted.inc()
                raise KVPagesExhaustedError(
                    f"KV pool exhausted: need {grow} more pages for "
                    f"{owner!r}, {len(self._free)} free of "
                    f"{self.n_pages}")
            for _ in range(grow):
                page = self._free.pop()
                self._owner[page] = owner
                table.append(page)
            self._c_alloc.inc(grow)
            return list(table)

    def release(self, owner):
        """Recycle every page ``owner`` holds (the sequence left the
        batch); returns the number freed. Unknown owners free 0 —
        release is idempotent by design (leave paths can race stop)."""
        with self._lock:
            table = self._tables.pop(owner, None)
            if not table:
                return 0
            for page in table:
                self._owner[page] = None
                self._free.append(page)
            self._c_free.inc(len(table))
            return len(table)

    # -- inspection --------------------------------------------------------
    def table(self, owner):
        """``owner``'s page table (a copy), or None."""
        with self._lock:
            t = self._tables.get(owner)
            return list(t) if t is not None else None

    def owner_of(self, page):
        with self._lock:
            return self._owner[int(page)]

    def occupancy(self):
        """Pool occupancy snapshot — the /stats + bench number."""
        with self._lock:
            used = self.n_pages - len(self._free)
            owners = len(self._tables)
        return {"pages_total": self.n_pages, "pages_used": used,
                "pages_free": self.n_pages - used, "sequences": owners,
                "page_size": self.page_size,
                "occupancy": round(used / float(self.n_pages), 4)}

    def check_isolated(self):
        """Assert the attribution invariants: live tables are pairwise
        disjoint, every table page is attributed to its owner, free
        pages are unowned, and used + free == total. Raises
        ``AssertionError`` on violation (tests and drills call this;
        production code paths maintain it by construction)."""
        with self._lock:
            seen = {}
            for owner, table in self._tables.items():
                for page in table:
                    assert page not in seen, (
                        f"page {page} shared by {seen[page]!r} and "
                        f"{owner!r}")
                    seen[page] = owner
                    assert self._owner[page] == owner, (
                        f"page {page} attributed to "
                        f"{self._owner[page]!r}, tabled by {owner!r}")
            for page in self._free:
                assert self._owner[page] is None, (
                    f"free page {page} still attributed to "
                    f"{self._owner[page]!r}")
                assert page not in seen, f"free page {page} is tabled"
            assert len(seen) + len(self._free) == self.n_pages
        return True

    # -- batch views -------------------------------------------------------
    def padded_tables(self, owners, width):
        """(R, width) int32 page-table batch for the decode step: row
        r is ``owners[r]``'s table padded with the scratch page (the
        kernel's per-row kv_len mask keeps padding slots dead — but a
        PAD ROW's write must land somewhere no live sequence owns)."""
        out = np.full((len(owners), int(width)), self.scratch_page,
                      np.int32)
        with self._lock:
            for r, owner in enumerate(owners):
                table = self._tables.get(owner, ())
                if len(table) > out.shape[1]:
                    raise ValueError(
                        f"table width {width} cannot hold {owner!r}'s "
                        f"{len(table)} pages")
                out[r, :len(table)] = table
        return out

    def scatter_indices(self, owner, valid, padded=None):
        """(physical_page, offset) int32 arrays addressing logical
        positions ``0 .. padded-1`` of ``owner``'s sequence — the
        prefill writer's scatter coordinates. Positions at/after
        ``valid`` (the padded tail of a bucketed prefill) map to the
        scratch page, so one compile per padded length serves every
        request in the bucket. The table must already cover ``valid``
        tokens (call :meth:`ensure` first)."""
        padded = int(valid) if padded is None else int(padded)
        pos = np.arange(padded)
        logical = pos // self.page_size
        with self._lock:
            table = np.asarray(self._tables.get(owner, ()), np.int64)
        need = self.pages_for(valid)
        if need > len(table):
            raise ValueError(
                f"{owner!r}'s table ({len(table)} pages) does not "
                f"cover {valid} tokens")
        phys = np.full(padded, self.scratch_page, np.int64)
        live = pos < int(valid)
        phys[live] = table[logical[live]]
        return phys.astype(np.int32), (pos % self.page_size).astype(
            np.int32)

    def swap(self, caches):
        """Install the jitted step's returned cache arrays (the donated
        inputs are dead after the call)."""
        if len(caches) != len(self.caches):
            raise ValueError("cache arity mismatch")
        self.caches = tuple(caches)
