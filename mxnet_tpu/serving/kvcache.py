"""Paged KV cache: the decode engine's attention memory.

The vLLM idea, sized for this serving engine: instead of one
contiguous (max_len) KV buffer per sequence — whose worst-case
reservation wastes most of the pool on short chats — the cache is a
POOL of fixed-size pages (``MXNET_TPU_KV_PAGE_SIZE`` tokens each,
``MXNET_TPU_KV_PAGES`` total), preallocated once per layer as
``(P, H, page_size, D)`` device arrays. Each live sequence owns a
PAGE TABLE (an ordered list of physical page ids); growing past a
page boundary allocates exactly one more page, and a finished
sequence returns its pages to the free list the same iteration it
leaves the batch — memory fragmentation is impossible by construction
(every page is the same size) and occupancy is a first-class metric.

Isolation is per-page OWNER ATTRIBUTION, generalized to owner SETS:
a page is PRIVATE to one sequence while it is being written, but a
FULL page holding prompt-prefix K/V may be shared READ-ONLY by every
request whose prompt starts with the same tokens (production traffic
shares system prompts; recomputing their K/V per request is the
single largest avoidable prefill cost). Sharing is refcounted —
``refcount = live owners + (1 if the prefix index pins it)`` — with
copy-on-write the moment a shared page would be written: a prompt
diverging mid-page, or a shared page sitting at a sequence's write
frontier, gets a private copy first (:meth:`PagedKVPool.prepare_write`
/ the COW arm of :meth:`PagedKVPool.match_prefix`). A page recycles
to the free list exactly when its refcount hits zero. PARTIAL pages
are never shared: only pages whose every slot holds verified prompt
tokens enter the index, so the write frontier of one sequence can
never alias another's history. :meth:`PagedKVPool.check_isolated`
asserts all of it (consistent attribution both ways, shared pages at
identical table positions, free pages unreferenced) — the decode
analog of the packed encoder path's segment ids. The decode kernel
(``ops.pallas.flash_attention.paged_flash_attention``) then reads K/V
through the table with per-row ``kv_len`` masking, so one sequence
can never attend into another's pages even though they share the
physical pool.

The PREFIX INDEX is a bounded LRU (``MXNET_TPU_KV_PREFIX_PAGES``
entries, ``MXNET_TPU_KV_PREFIX`` gates the whole feature) keyed by a
sha1 CHAIN over page-granular prompt slices — entry ``i``'s key
hashes page ``i``'s tokens with page ``i-1``'s key, so a digest match
plus the stored per-page token comparison verifies the entire prefix
without storing O(prefix²) tokens. Index pins survive the owning
sequence (that is the cache value: the next same-prompt request hits
pages a finished one computed), but pinned-unowned pages are
reclaimed on demand when the free list runs dry — cached prefixes
give way to live sequences, never the reverse. Hits, misses,
evictions and COW copies are counted per engine
(``mxnet_tpu_serving_kv_prefix_events_total``) and the occupancy
gauge splits ``shared`` vs ``private`` page states.

The pool's arrays flow THROUGH the jitted decode/prefill steps as
donated buffers (``jax.jit(..., donate_argnums=...)``): the step
consumes the old cache arrays and returns the updated ones, XLA
reuses the storage, and steady-state decode performs no per-step
cache-sized allocation (the resource-watermark assertion in
tests/test_decode.py pins this). COW copies ride the same contract
(:meth:`PagedKVPool.copy_pages` — call it under the engine's forward
lock, like any step that swaps the caches).
"""
from __future__ import annotations

import hashlib
import threading
import warnings
from collections import OrderedDict

import numpy as np

from .. import envvars
from ..telemetry.registry import REGISTRY
from .queue import ServingError

__all__ = ["KVPagesExhaustedError", "PagedKVPool"]

# XLA CPU cannot honor buffer donation (TPU/GPU can); jax warns once
# per compile — expected off-chip, pure noise in CPU test logs
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


class KVPagesExhaustedError(ServingError):
    """The page pool cannot hold another page: backpressure for the
    decode admission path (the engine defers the join — the request
    waits in the prefill queue until pages recycle)."""


def _kv_pages_gauge(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.gauge(
        "mxnet_tpu_serving_kv_pages",
        "paged KV cache pool pages by state: used/free plus the "
        "used split shared (read-only prefix pages, frozen) vs "
        "private (single-owner, writable) and cached (index-pinned "
        "with no live owner), per engine",
        ("engine_id", "state"))


def _kv_events_counter(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.counter(
        "mxnet_tpu_serving_kv_page_events_total",
        "paged KV cache pool events: alloc/free (pages) and exhausted "
        "(refused allocations), per engine", ("engine_id", "event"))


def _kv_prefix_counter(registry=None):
    reg = registry if registry is not None else REGISTRY
    return reg.counter(
        "mxnet_tpu_serving_kv_prefix_events_total",
        "prefix-KV-reuse events: hit/miss (prompt lookups), evict "
        "(LRU index entries dropped) and cow (copy-on-write page "
        "copies), per engine", ("engine_id", "event"))


def _copy_page_impl(caches, src, dst):
    """Device-side page copy: every (K, V) array gets page ``src``'s
    content written into page ``dst`` — the COW data move."""
    return tuple(c.at[dst].set(c[src]) for c in caches)


_copy_jit = {}
_copy_jit_lock = threading.Lock()


def _copy_step(donate):
    with _copy_jit_lock:
        fn = _copy_jit.get(donate)
        if fn is None:
            import jax

            kw = {"donate_argnums": (0,)} if donate else {}
            fn = jax.jit(_copy_page_impl, **kw)
            _copy_jit[donate] = fn
        return fn


# chain root: page 0's key hashes its tokens against this sentinel
_ROOT = b"kv-prefix-root"


class PagedKVPool:
    """Fixed-size-page KV pool with per-sequence page tables and a
    refcounted prefix-sharing index.

    Parameters
    ----------
    n_layers / n_heads / head_dim : the model's KV geometry — one
        (K, V) page array pair per layer, shaped
        ``(n_pages, n_heads, page_size, head_dim)``.
    page_size : tokens per page (default ``MXNET_TPU_KV_PAGE_SIZE``).
    n_pages : pool capacity (default ``MXNET_TPU_KV_PAGES``).
    dtype : cache dtype (the model's activation dtype).
    engine_id : label for the pool's metric families.
    prefix_cache : enable prefix-KV sharing (default
        ``MXNET_TPU_KV_PREFIX``).
    prefix_pages : LRU index capacity in entries (default
        ``MXNET_TPU_KV_PREFIX_PAGES``).

    ``caches`` is a flat tuple ``(k0, v0, k1, v1, ...)`` — the pytree
    the jitted decode step takes as its DONATED first argument and
    returns updated; the engine writes the returned tuple back with
    :meth:`swap`. All bookkeeping (free list, tables, owner sets,
    prefix index) is host-side, thread-safe under one lock; array
    contents are only ever touched inside the jitted steps.
    """

    def __init__(self, n_layers, n_heads, head_dim, page_size=None,
                 n_pages=None, dtype="float32", engine_id="default",
                 registry=None, prefix_cache=None, prefix_pages=None):
        import jax.numpy as jnp

        self.page_size = int(page_size if page_size is not None
                             else envvars.get("MXNET_TPU_KV_PAGE_SIZE"))
        self.n_pages = int(n_pages if n_pages is not None
                           else envvars.get("MXNET_TPU_KV_PAGES"))
        if self.page_size < 1 or self.n_pages < 1:
            raise ValueError(
                f"bad page pool geometry: {self.n_pages} pages of "
                f"{self.page_size} tokens")
        self.n_layers = int(n_layers)
        self.engine_id = str(engine_id)
        self.prefix_enabled = bool(
            envvars.get("MXNET_TPU_KV_PREFIX") if prefix_cache is None
            else prefix_cache)
        self.prefix_cap = int(
            envvars.get("MXNET_TPU_KV_PREFIX_PAGES")
            if prefix_pages is None else prefix_pages)
        if self.prefix_cap < 1:
            self.prefix_enabled = False
        self._donate = envvars.get("MXNET_TPU_DECODE_DONATE")
        # one extra SCRATCH page (id n_pages, never allocated): padded
        # decode-batch rows and prefill tail padding write there, so a
        # dummy row can never clobber a live sequence's page
        self.scratch_page = self.n_pages
        shape = (self.n_pages + 1, int(n_heads), self.page_size,
                 int(head_dim))
        self.caches = tuple(
            jnp.zeros(shape, dtype=jnp.dtype(dtype))
            for _ in range(2 * self.n_layers))
        self._lock = threading.Lock()
        # LIFO free list: a just-freed (cache-warm) page is reused first
        self._free = list(range(self.n_pages - 1, -1, -1))
        self._tables = {}               # owner -> [page ids] in order
        # per-page owner SETS (+1: the scratch page, never owned)
        self._owners = [set() for _ in range(self.n_pages + 1)]
        # prefix index: chain-digest -> {page, tokens, parent}; LRU in
        # insertion/touch order. _pinned maps page -> its index key,
        # _children maps parent key -> child keys (the partial-match /
        # divergence walk needs them; digests alone can't be computed
        # for a page whose tokens only partly match).
        self._prefix = OrderedDict()
        self._pinned = {}
        self._children = {}
        self._pstats = {"lookups": 0, "hits": 0, "misses": 0,
                        "pages_reused": 0, "tokens_reused": 0,
                        "cow_pages": 0, "evictions": 0, "inserts": 0}
        ev = _kv_events_counter(registry)
        self._c_alloc = ev.labels(engine_id=self.engine_id, event="alloc")
        self._c_free = ev.labels(engine_id=self.engine_id, event="free")
        self._c_exhausted = ev.labels(engine_id=self.engine_id,
                                      event="exhausted")
        pv = _kv_prefix_counter(registry)
        self._c_hit = pv.labels(engine_id=self.engine_id, event="hit")
        self._c_miss = pv.labels(engine_id=self.engine_id, event="miss")
        self._c_evict = pv.labels(engine_id=self.engine_id,
                                  event="evict")
        self._c_cow = pv.labels(engine_id=self.engine_id, event="cow")
        g = _kv_pages_gauge(registry)
        # pull gauges: scrape-time reads, zero hot-path cost
        g.labels(engine_id=self.engine_id, state="used") \
            .set_function(lambda: self._count_states()["used"])
        g.labels(engine_id=self.engine_id, state="free") \
            .set_function(lambda: len(self._free))
        g.labels(engine_id=self.engine_id, state="shared") \
            .set_function(lambda: self._count_states()["shared"])
        g.labels(engine_id=self.engine_id, state="private") \
            .set_function(lambda: self._count_states()["private"])
        g.labels(engine_id=self.engine_id, state="cached") \
            .set_function(lambda: self._count_states()["cached"])

    # -- geometry ----------------------------------------------------------
    def pages_for(self, kv_len):
        """Pages needed to hold ``kv_len`` tokens."""
        return -(-int(kv_len) // self.page_size)

    @property
    def bytes_total(self):
        return sum(int(np.prod(c.shape)) * c.dtype.itemsize
                   for c in self.caches)

    def _count_states(self):
        """used/shared/private/cached page counts. Lock-free reads of
        the owner sets (pull-gauge scrapes tolerate a page mid-
        transition; the sums are exact the instant nothing moves)."""
        used = shared = cached = 0
        for page in range(self.n_pages):
            owners = len(self._owners[page])
            pinned = page in self._pinned
            if owners:
                used += 1
                if pinned or owners > 1:
                    shared += 1
            elif pinned:
                cached += 1
        return {"used": used, "shared": shared,
                "private": used - shared, "cached": cached}

    # -- allocation --------------------------------------------------------
    def _reclaim_locked(self, need):
        """Evict LRU index entries whose pages have no live owner
        until ``need`` pages are free (or no reclaimable entry is
        left). Cached prefixes yield to live sequences on demand —
        the index can never starve admission."""
        if need <= len(self._free):
            return
        for key in list(self._prefix):
            if len(self._free) >= need:
                break
            if not self._owners[self._prefix[key]["page"]]:
                self._evict_locked(key)

    def _evict_locked(self, key):
        """Drop one index entry: unpin its page (recycling it if no
        sequence still owns it) and unlink it from the chain."""
        entry = self._prefix.pop(key)
        page = entry["page"]
        self._pinned.pop(page, None)
        kids = self._children.get(entry["parent"])
        if kids is not None:
            kids.discard(key)
            if not kids:
                self._children.pop(entry["parent"], None)
        self._pstats["evictions"] += 1
        self._c_evict.inc()
        if not self._owners[page]:
            self._free.append(page)
            self._c_free.inc()

    def _alloc_locked(self, owner, n):
        """Pop ``n`` free pages for ``owner`` (reclaiming cached
        prefix pages if the free list is short); atomic — raises
        :class:`KVPagesExhaustedError` allocating nothing when the
        pool genuinely cannot hold them."""
        self._reclaim_locked(n)
        if n > len(self._free):
            self._c_exhausted.inc()
            raise KVPagesExhaustedError(
                f"KV pool exhausted: need {n} more pages for "
                f"{owner!r}, {len(self._free)} free of {self.n_pages}")
        out = []
        for _ in range(n):
            page = self._free.pop()
            self._owners[page].add(owner)
            out.append(page)
        self._c_alloc.inc(n)
        return out

    def ensure(self, owner, kv_len):
        """Grow ``owner``'s table to hold ``kv_len`` tokens; returns
        the table. Atomic: either every page needed is allocated or
        none is (:class:`KVPagesExhaustedError`) — a half-grown
        sequence could never run its next step."""
        need_pages = self.pages_for(kv_len)
        with self._lock:
            table = self._tables.setdefault(owner, [])
            grow = need_pages - len(table)
            if grow <= 0:
                return list(table)
            table.extend(self._alloc_locked(owner, grow))
            return list(table)

    def release(self, owner):
        """Drop ``owner``'s reference on every page it holds (the
        sequence left the batch); pages whose refcount hits zero
        recycle immediately, index-pinned ones stay cached for the
        next same-prefix prompt. Returns the number recycled. Unknown
        owners free 0 — release is idempotent by design (leave paths
        can race stop)."""
        with self._lock:
            table = self._tables.pop(owner, None)
            if not table:
                return 0
            freed = 0
            for page in table:
                self._owners[page].discard(owner)
                if not self._owners[page] and page not in self._pinned:
                    self._free.append(page)
                    freed += 1
            if freed:
                self._c_free.inc(freed)
            return freed

    # -- prefix sharing ----------------------------------------------------
    def _chain_key(self, parent, tokens):
        return hashlib.sha1(parent + np.ascontiguousarray(
            tokens, np.int32).tobytes()).digest()

    def match_prefix(self, owner, tokens):
        """Attach the longest cached prefix of ``tokens`` to
        ``owner``'s (empty) table. Returns ``(matched, copies)``:
        ``matched`` tokens of prompt K/V the prefill can skip, and
        ``copies`` — ``(src, dst)`` page pairs the caller MUST
        materialize with :meth:`copy_pages` before any step reads
        ``owner``'s table (the COW arm).

        Fully-matching FULL pages are attached read-only (the owner
        joins the page's owner set — zero data movement). The first
        page that matches only partially — the prompt diverges
        mid-page, or simply ends inside it — is COW-attached: a
        private copy carries the matched slots and the prefill
        overwrites the rest, so a partial match still saves its
        tokens without ever sharing a partially-valid page. At least
        one prompt token is always left to prefill — the first
        generated token's logits come from it."""
        toks = np.ascontiguousarray(tokens, np.int32).ravel()
        ps = self.page_size
        limit = int(toks.size) - 1     # last prompt token never reused
        with self._lock:
            if not self.prefix_enabled or limit < 1:
                return 0, []
            table = self._tables.setdefault(owner, [])
            if table:
                raise ValueError(
                    f"match_prefix on a non-empty table ({owner!r})")
            self._pstats["lookups"] += 1
            matched, copies, parent = 0, [], _ROOT
            while matched < limit:
                lo = matched
                want = toks[lo:lo + ps]
                cap = min(ps, limit - lo)      # usable tokens here
                key = self._chain_key(parent, want)
                entry = self._prefix.get(key)
                if (cap == ps and entry is not None
                        and np.array_equal(entry["tokens"], want)):
                    # whole page verified: share read-only
                    page = entry["page"]
                    self._owners[page].add(owner)
                    table.append(page)
                    self._prefix.move_to_end(key)
                    matched += ps
                    parent = key
                    self._pstats["pages_reused"] += 1
                    continue
                # tail page: find the child sharing the longest
                # sub-page prefix (divergence / prompt-end mid-page)
                best, best_m = None, 0
                for ck in self._children.get(parent, ()):
                    ce = self._prefix.get(ck)
                    if ce is None:
                        continue
                    et = ce["tokens"][:cap]
                    m = int((np.cumprod(et == want[:et.size])).sum())
                    if m > best_m:
                        best, best_m = ce, m
                if best is not None and best_m >= 1:
                    try:
                        dst = self._alloc_locked(owner, 1)[0]
                    except KVPagesExhaustedError:
                        break          # partial reuse is best-effort
                    table.append(dst)
                    copies.append((best["page"], dst))
                    self._prefix.move_to_end(
                        self._pinned[best["page"]])
                    matched += best_m
                    self._pstats["pages_reused"] += 1
                    self._pstats["cow_pages"] += 1
                    self._c_cow.inc()
                break
            if matched:
                self._pstats["hits"] += 1
                self._pstats["tokens_reused"] += matched
                self._c_hit.inc()
            else:
                self._pstats["misses"] += 1
                self._c_miss.inc()
            return matched, copies

    def register_prefix(self, owner, tokens):
        """Index every FULL prompt page of ``owner``'s freshly
        prefilled sequence (called once the whole prompt's K/V is in
        the pages). Pages already indexed (attached via
        :meth:`match_prefix`) are just LRU-refreshed; new entries pin
        their page — the pin is the cache's refcount, outliving the
        sequence. The LRU bound evicts the oldest entry beyond
        ``prefix_pages``. Partial pages (a prompt ending mid-page)
        are NEVER registered: every indexed slot holds verified
        prompt tokens."""
        toks = np.ascontiguousarray(tokens, np.int32).ravel()
        ps = self.page_size
        with self._lock:
            if not self.prefix_enabled:
                return 0
            table = self._tables.get(owner, ())
            parent, added = _ROOT, 0
            for i in range(int(toks.size) // ps):
                want = toks[i * ps:(i + 1) * ps]
                key = self._chain_key(parent, want)
                entry = self._prefix.get(key)
                if entry is not None:
                    self._prefix.move_to_end(key)
                    parent = key
                    continue
                if i >= len(table):
                    break
                page = table[i]
                if page in self._pinned:
                    # same physical page under a different chain key
                    # (a COW copy whose content since diverged can't
                    # happen for full prompt pages, but stay safe)
                    parent = key
                    continue
                self._prefix[key] = {"page": page,
                                     "tokens": want.copy(),
                                     "parent": parent}
                self._pinned[page] = key
                self._children.setdefault(parent, set()).add(key)
                self._pstats["inserts"] += 1
                added += 1
                parent = key
            while len(self._prefix) > self.prefix_cap:
                self._evict_locked(next(iter(self._prefix)))
            return added

    def prepare_write(self, owner, pos):
        """Make the page holding logical position ``pos`` of
        ``owner``'s sequence privately writable. A private page
        returns None (the fast path — one set-membership check). A
        FROZEN page (index-pinned or multi-owner: a shared prefix
        page that became this sequence's write frontier) is COW'd:
        the owner gets a fresh page in its table slot and the
        returned ``(src, dst)`` pair must be materialized with
        :meth:`copy_pages` before the next step."""
        idx = int(pos) // self.page_size
        with self._lock:
            table = self._tables.get(owner)
            if table is None or idx >= len(table):
                raise ValueError(
                    f"{owner!r}'s table does not cover position {pos}"
                    " (ensure first)")
            page = table[idx]
            frozen = page in self._pinned or len(self._owners[page]) > 1
            if not frozen:
                return None
            dst = self._alloc_locked(owner, 1)[0]
            table[idx] = dst
            self._owners[page].discard(owner)
            if not self._owners[page] and page not in self._pinned:
                self._free.append(page)
                self._c_free.inc()
            self._pstats["cow_pages"] += 1
            self._c_cow.inc()
            return page, dst

    def copy_pages(self, pairs):
        """Materialize COW copies device-side: for each ``(src,
        dst)``, page ``src``'s K/V content lands in page ``dst``
        across every layer, through the same donated-buffer jit
        contract as the model steps. The CALLER must hold whatever
        lock serializes model steps against cache swaps (the engine's
        forward lock) — this swaps the cache tuple."""
        if not pairs:
            return
        import jax.numpy as jnp

        step = _copy_step(bool(self._donate))
        caches = self.caches
        for src, dst in pairs:
            caches = step(caches, jnp.asarray(int(src), jnp.int32),
                          jnp.asarray(int(dst), jnp.int32))
        self.swap(caches)

    def prefix_stats(self):
        """Prefix-index observability snapshot (scheduler-state
        flight-bundle section, loadgen report, /stats)."""
        with self._lock:
            st = dict(self._pstats)
            st["enabled"] = self.prefix_enabled
            st["entries"] = len(self._prefix)
            st["capacity"] = self.prefix_cap
            looked = st["hits"] + st["misses"]
            st["hit_rate"] = (round(st["hits"] / looked, 4)
                              if looked else None)
            return st

    def page_refcounts(self):
        """Per-page refcounts for every referenced page: owner count
        + index pin — the flight-bundle drill-down for a stuck or
        leaking pool."""
        with self._lock:
            out = {}
            for page in range(self.n_pages):
                owners = self._owners[page]
                pinned = page in self._pinned
                if owners or pinned:
                    out[page] = {"refs": len(owners) + int(pinned),
                                 "owners": len(owners),
                                 "pinned": pinned}
            return out

    # -- inspection --------------------------------------------------------
    def table(self, owner):
        """``owner``'s page table (a copy), or None."""
        with self._lock:
            t = self._tables.get(owner)
            return list(t) if t is not None else None

    def owner_of(self, page):
        """The page's SOLE owner, or None (free, cached, or shared by
        several — use :meth:`owners_of` for the full set)."""
        with self._lock:
            owners = self._owners[int(page)]
            return next(iter(owners)) if len(owners) == 1 else None

    def owners_of(self, page):
        """Every live sequence referencing ``page`` (frozen view)."""
        with self._lock:
            return frozenset(self._owners[int(page)])

    def occupancy(self):
        """Pool occupancy snapshot — the /stats + bench number.
        ``pages_used`` counts pages referenced by LIVE sequences;
        index-pinned pages with no owner report as ``pages_cached``
        (they recycle on demand, so they are headroom, not load)."""
        with self._lock:
            st = self._count_states()
            owners = len(self._tables)
            entries = len(self._prefix)
        return {"pages_total": self.n_pages, "pages_used": st["used"],
                "pages_free": len(self._free),
                "pages_shared": st["shared"],
                "pages_private": st["private"],
                "pages_cached": st["cached"],
                "prefix_entries": entries,
                "sequences": owners,
                "page_size": self.page_size,
                "occupancy": round(st["used"] / float(self.n_pages), 4)}

    def check_isolated(self):
        """Assert the attribution invariants, generalized to owner
        sets: every tabled page is attributed to that owner and vice
        versa; a page shared by several sequences sits at the SAME
        table index in each (prefix pages — position is content);
        free pages are unreferenced (no owner, no pin, no table);
        pinned pages are the ones their index entries name; and
        referenced + free == total. Raises ``AssertionError`` on
        violation (tests and drills call this; production code paths
        maintain it by construction)."""
        with self._lock:
            positions = {}               # page -> table index
            tabled = set()
            for owner, table in self._tables.items():
                for idx, page in enumerate(table):
                    assert owner in self._owners[page], (
                        f"page {page} tabled by {owner!r} but not "
                        f"attributed to it ({self._owners[page]!r})")
                    if page in positions:
                        assert positions[page] == idx, (
                            f"shared page {page} at table index {idx} "
                            f"for {owner!r} but {positions[page]} "
                            f"elsewhere")
                    positions[page] = idx
                    tabled.add(page)
            for page in range(self.n_pages):
                for owner in self._owners[page]:
                    assert page in self._tables.get(owner, ()), (
                        f"page {page} attributed to {owner!r} but "
                        f"missing from its table")
            for page in self._free:
                assert not self._owners[page], (
                    f"free page {page} still attributed to "
                    f"{self._owners[page]!r}")
                assert page not in self._pinned, (
                    f"free page {page} still pinned by the prefix "
                    f"index")
                assert page not in tabled, f"free page {page} is tabled"
            for key, entry in self._prefix.items():
                assert self._pinned.get(entry["page"]) == key, (
                    f"index entry for page {entry['page']} out of "
                    f"sync with its pin")
            referenced = {p for p in range(self.n_pages)
                          if self._owners[p] or p in self._pinned}
            assert tabled <= referenced
            assert len(referenced) + len(self._free) == self.n_pages, (
                f"{len(referenced)} referenced + {len(self._free)} "
                f"free != {self.n_pages}")
            assert not self._owners[self.scratch_page]
            assert self.scratch_page not in self._pinned
        return True

    # -- batch views -------------------------------------------------------
    def padded_tables(self, owners, width):
        """(R, width) int32 page-table batch for the decode step: row
        r is ``owners[r]``'s table padded with the scratch page (the
        kernel's per-row kv_len mask keeps padding slots dead — but a
        PAD ROW's write must land somewhere no live sequence owns)."""
        out = np.full((len(owners), int(width)), self.scratch_page,
                      np.int32)
        with self._lock:
            for r, owner in enumerate(owners):
                table = self._tables.get(owner, ())
                if len(table) > out.shape[1]:
                    raise ValueError(
                        f"table width {width} cannot hold {owner!r}'s "
                        f"{len(table)} pages")
                out[r, :len(table)] = table
        return out

    def scatter_indices(self, owner, valid, padded=None, start=0):
        """(physical_page, offset) int32 arrays addressing ``valid``
        logical positions of ``owner``'s sequence — the prefill
        writer's scatter coordinates. Entry ``i < valid`` addresses
        position ``start + i`` (``start=0`` is whole-prompt prefill;
        ``start > 0`` a chunked-prefill slice, FRONT-aligned like the
        chunk step's ids row); entries at/after ``valid`` map to the
        scratch page. The table must already cover ``start + valid``
        tokens (call :meth:`ensure` first)."""
        padded = int(valid) if padded is None else int(padded)
        start, valid = int(start), int(valid)
        idx = np.arange(padded)
        pos = start + idx
        live = idx < valid
        with self._lock:
            table = np.asarray(self._tables.get(owner, ()), np.int64)
        need = self.pages_for(start + valid)
        if need > len(table):
            raise ValueError(
                f"{owner!r}'s table ({len(table)} pages) does not "
                f"cover {start + valid} tokens")
        phys = np.full(padded, self.scratch_page, np.int64)
        phys[live] = table[pos[live] // self.page_size]
        off = pos % self.page_size
        return phys.astype(np.int32), off.astype(np.int32)

    def swap(self, caches):
        """Install the jitted step's returned cache arrays (the donated
        inputs are dead after the call)."""
        if len(caches) != len(self.caches):
            raise ValueError("cache arity mismatch")
        self.caches = tuple(caches)
