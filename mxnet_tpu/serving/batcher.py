"""Continuous batcher: first-fit-pack drained requests into a small
set of fixed packed-row shapes.

The shape story is the whole point. A compiled executor exists per
input shape (gluon's CachedOp caches per shape key — the reference's
BucketingModule heritage), so the batcher must emit batches from a
SMALL closed set of shapes or every traffic mix would compile a fresh
executable. Two quantizations bound that set:

- ``bucket_lens``: the row length is the smallest configured bucket
  that holds the longest request in the batch;
- row COUNT is rounded up to a power of two (capped at ``max_rows``),
  padding with 1-token dummy rows.

Total shapes = len(bucket_lens) x (log2(max_rows)+1). Within a row,
requests are packed first-fit (io/packing.py) and isolated by the
flash kernel's ``segment_ids`` path — no request pays padding it
didn't bring, which is what turns ISSUE-1's training optimisation
into a serving throughput win.
"""
from __future__ import annotations

import numpy as np

from ..io.packing import pack_sequences

__all__ = ["PackedPlan", "ContinuousBatcher", "DecodeSlots",
           "PrefillChunks"]


class PackedPlan:
    """One dispatchable batch: packed arrays + request placements."""

    __slots__ = ("data", "token_types", "segment_ids", "positions",
                 "valid_length", "entries", "rows", "row_len",
                 "valid_tokens", "pad_rows")

    def __init__(self, data, token_types, segment_ids, positions,
                 valid_length, entries, pad_rows):
        self.data = data
        self.token_types = token_types
        self.segment_ids = segment_ids
        self.positions = positions
        self.valid_length = valid_length
        self.entries = entries            # [(request, Placement)]
        self.rows, self.row_len = data.shape
        self.valid_tokens = sum(len(r) for r, _ in entries)
        self.pad_rows = pad_rows

    @property
    def packing_efficiency(self):
        return self.valid_tokens / float(self.rows * self.row_len)


class ContinuousBatcher:
    """Stateless planner: ``plan(requests)`` → (PackedPlan, leftovers).

    Leftovers are requests that did not fit this batch (all rows full);
    the engine carries them into the next iteration at the front of the
    line — nothing is ever dropped here (dropping is the queue's and
    deadline checker's job, where it is loud).
    """

    def __init__(self, bucket_lens=(64, 256, 1024), max_rows=8,
                 quantize_rows=True, pad_value=0):
        lens = sorted(set(int(b) for b in bucket_lens))
        if not lens or lens[0] < 1:
            raise ValueError(f"bad bucket_lens {bucket_lens!r}")
        if max_rows < 1:
            raise ValueError("max_rows must be >= 1")
        self.bucket_lens = tuple(lens)
        self.max_len = lens[-1]
        self.max_rows = int(max_rows)
        self.quantize_rows = quantize_rows
        self.pad_value = pad_value

    def shape_universe(self):
        """Every (rows, row_len) this batcher can emit — the compile
        budget, enumerable up front for warmup."""
        rows = []
        r = 1
        while r < self.max_rows:
            rows.append(r)
            r *= 2
        rows.append(self.max_rows)
        if not self.quantize_rows:
            rows = list(range(1, self.max_rows + 1))
        return [(r, b) for b in self.bucket_lens for r in sorted(set(rows))]

    def _bucket_for(self, n):
        for b in self.bucket_lens:
            if n <= b:
                return b
        return None

    def _quantized_rows(self, used_rows):
        if not self.quantize_rows:
            return used_rows
        r = 1
        while r < used_rows:
            r *= 2
        return min(r, self.max_rows)

    def plan(self, requests):
        """First-fit as many of ``requests`` (in order) as fit
        ``max_rows`` rows of the chosen bucket length."""
        if not requests:
            return None, []
        row_len = self._bucket_for(max(len(r) for r in requests))
        if row_len is None:
            # the engine rejects oversize requests at admission; this
            # is a belt-and-suspenders guard for direct batcher users
            fits = [r for r in requests if len(r) <= self.max_len]
            rest = [r for r in requests if len(r) > self.max_len]
            plan, leftover = self.plan(fits)
            return plan, leftover + rest
        used = []                       # slots consumed per open row
        accepted, leftover = [], []
        for r in requests:
            n = len(r)
            for i in range(len(used)):  # first fit
                if used[i] + n <= row_len:
                    used[i] += n
                    accepted.append(r)
                    break
            else:
                if len(used) < self.max_rows:
                    used.append(n)
                    accepted.append(r)
                else:
                    leftover.append(r)
        tts = [r.token_types if r.token_types is not None
               else np.zeros(len(r), np.int32) for r in accepted]
        batch = pack_sequences([r.tokens for r in accepted], row_len,
                               extras=[tts], pad_value=self.pad_value,
                               dtype=np.int32, max_rows=self.max_rows)
        rows = self._quantized_rows(batch.data.shape[0])
        pad_rows = rows - batch.data.shape[0]
        data = _pad_rows(batch.data, pad_rows, self.pad_value)
        seg = _pad_rows(batch.segment_ids, pad_rows, 0)
        pos = _pad_rows(batch.positions, pad_rows, 0)
        tt = _pad_rows(batch.extras[0], pad_rows, 0)
        vl = np.concatenate([batch.valid_length,
                             np.ones(pad_rows, np.int32)]) \
            if pad_rows else batch.valid_length
        # dummy rows carry ONE 1-token segment so no row reaches the
        # kernel with zero valid keys (an all-masked softmax row)
        for i in range(batch.data.shape[0], rows):
            seg[i, 0] = 1
        return PackedPlan(data, tt, seg, pos, vl,
                          list(zip(accepted, batch.placements)),
                          pad_rows), leftover


def _pad_rows(arr, pad_rows, fill):
    if not pad_rows:
        return arr
    return np.concatenate(
        [arr, np.full((pad_rows,) + arr.shape[1:], fill, arr.dtype)])


def _pow2_up_to(cap):
    out, v = [], 1
    while v < cap:
        out.append(v)
        v *= 2
    out.append(int(cap))
    return sorted(set(out))


class DecodeSlots:
    """Closed (rows × table-width) bucket set for the decode batch.

    The encoder batcher above quantizes (rows, row_len); the decode
    loop's shape axes are the ROW COUNT of the iteration batch and the
    WIDTH of the padded page-table operand (pages of the longest
    member sequence). Both quantize to powers of two — rows capped at
    ``max_rows`` (the slot budget), width at ``max_pages`` (the pages
    a ``max_len`` sequence needs) — so the jitted decode step compiles
    ``log2(max_rows) x log2(max_pages)`` executables, enumerable up
    front for warmup, and a sequence crossing a page boundary reuses
    the next bucket's executable instead of tracing a fresh one.
    """

    def __init__(self, max_rows=8, max_pages=8):
        if max_rows < 1 or max_pages < 1:
            raise ValueError(
                f"bad decode slot geometry: rows {max_rows}, pages "
                f"{max_pages}")
        self.max_rows = int(max_rows)
        self.max_pages = int(max_pages)
        self._rows = _pow2_up_to(self.max_rows)
        self._widths = _pow2_up_to(self.max_pages)

    def bucket(self, n_rows, n_pages):
        """The (rows, width) bucket holding an ``n_rows``-sequence
        iteration whose longest member spans ``n_pages`` pages."""
        if n_rows < 1 or n_rows > self.max_rows:
            raise ValueError(f"{n_rows} rows outside 1..{self.max_rows}")
        if n_pages < 1 or n_pages > self.max_pages:
            raise ValueError(
                f"{n_pages} pages outside 1..{self.max_pages}")
        rows = next(r for r in self._rows if r >= n_rows)
        width = next(w for w in self._widths if w >= n_pages)
        return rows, width

    def shape_universe(self):
        """Every (rows, width) the decode loop can emit — the compile
        budget, enumerable for warmup."""
        return [(r, w) for r in self._rows for w in self._widths]


class PrefillChunks:
    """Closed (chunk × table-width) bucket set for CHUNKED prefill.

    The chunked-prefill step's shape axes are the padded chunk length
    Sq (the kernel's query-block size) and the padded page-table
    WIDTH — the width axis power-of-two quantized like
    :class:`DecodeSlots`, the chunk axis a SINGLE bucket: the
    pow2-padded per-iteration prefill budget. A ladder of smaller
    chunk rungs would pad less for short takes, but every rung
    multiplies the compile universe by the whole width ladder, and
    the kernel's valid-row mask makes the padding free anyway —
    measured on the CPU suite, the ladder tripled warmup-heavy tests.
    Widths reuse the decode slots' ladder, so the warmup manifest
    absorbs the new buckets through the same (rows × width) machinery.
    Bucket keys are ``(-chunk, width)`` — the NEGATED first element
    keeps chunk shapes disjoint from dense-prefill ``(0, bucket)`` and
    decode ``(rows, width)`` keys in the one shape-universe namespace.
    """

    def __init__(self, budget=64, max_pages=8):
        if budget < 1 or max_pages < 1:
            raise ValueError(
                f"bad chunk geometry: budget {budget}, pages "
                f"{max_pages}")
        self.budget = int(budget)
        self._chunk = 1 << (self.budget - 1).bit_length()
        self._widths = _pow2_up_to(int(max_pages))
        self.max_pages = int(max_pages)

    def bucket(self, n_tokens, n_pages):
        """The (-chunk, width) bucket for a slice of ``n_tokens``
        prompt tokens whose sequence spans ``n_pages`` pages so far."""
        if n_tokens < 1 or n_tokens > self.budget:
            raise ValueError(
                f"{n_tokens} chunk tokens outside 1..{self.budget}")
        if n_pages < 1 or n_pages > self.max_pages:
            raise ValueError(
                f"{n_pages} pages outside 1..{self.max_pages}")
        width = next(w for w in self._widths if w >= n_pages)
        return -self._chunk, width

    def shape_universe(self):
        """Every (-chunk, width) the chunked-prefill path can emit."""
        return [(-self._chunk, w) for w in self._widths]
