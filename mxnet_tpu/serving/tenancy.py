"""Multi-tenant, multi-model serving control plane.

The fleet up to here was single-model/single-tenant: one entry-point
per engine, one admission class, one bill. Production traffic is
N models x M tenants with different priorities (ROADMAP direction 3),
so this module adds the two identity axes every layer below threads
through:

- :class:`ModelRegistry` — ``model_id -> (entry-point fn, version)``.
  One engine hosts several models; ``model_id`` rides SUBMIT wire
  frames, HTTP ``/submit``, router relays, the HA journal, shape /
  compile-cache keys and the canary golden index. ``swap()`` flips a
  model to a new fn/version atomically (the engine warm-replays the
  model's visited shapes first — see ``ServingEngine.swap_model``),
  which is the live hot-swap primitive: zero lost requests, and the
  version change re-TOFUs the router's canary golden via the seat
  token (``router._canary_targets``).

- Tenant **admission classes** with weighted-fair queuing:
  ``priority`` / ``standard`` / ``best-effort``, in that priority
  order. Each class has a WFQ weight (default 4/2/1 — overridable via
  ``MXNET_TPU_TENANT_WEIGHTS``), a depth budget (a fraction of the
  queue's ``max_depth``, ``MXNET_TPU_TENANT_DEPTH_SHARES``) and an
  optional default deadline (``MXNET_TPU_TENANT_DEADLINE_MS``). The
  WFQ scheduler itself lives in ``queue.RequestQueue``; this module
  owns the class vocabulary and the knob parsing.

- :class:`TenantStats` — the per-tenant/per-model observability
  slice: ``mxnet_tpu_serving_tenant_*`` registry families (every one
  carries ``engine_id`` + ``tenant`` + ``tenant_class`` + ``model``
  labels — the mxlint ``metric-tenant-label`` contract) and an
  in-process per-(tenant, model) ledger with derived
  ``device_s_per_1k_tokens`` bills, the number ``serve_loadgen``
  cross-checks against its client-side ledger.

The WFQ *class-depth* gauge is deliberately named
``mxnet_tpu_serving_wfq_queue_depth`` (outside the ``tenant_*``
prefix): it is keyed by class, not by tenant, so forcing the tenant
label on it would fan a bounded gauge into an unbounded one.
"""
from __future__ import annotations

import threading

from .. import envvars
from ..telemetry.registry import REGISTRY

__all__ = ["TENANT_CLASSES", "DEFAULT_CLASS_WEIGHTS", "DEFAULT_MODEL",
           "default_model_id", "normalize_class", "parse_class_map",
           "class_weights", "class_depth_shares", "class_deadline_ms",
           "class_slo_ms", "UnknownModelError", "ModelRegistry",
           "TenantStats", "wfq_depth_gauge"]

#: admission classes, HIGHEST priority first — this order is the WFQ
#: virtual-finish tie-break, the shed/expiry scan order (reversed),
#: and the dequeue order of ``RequestQueue.drain_all``
TENANT_CLASSES = ("priority", "standard", "best-effort")

DEFAULT_CLASS_WEIGHTS = {"priority": 4.0, "standard": 2.0,
                         "best-effort": 1.0}

#: the model id a single-model engine serves and a model-less submit
#: targets — resolved through ``MXNET_TPU_MODEL_DEFAULT``
DEFAULT_MODEL = "default"


def default_model_id():
    return str(envvars.get("MXNET_TPU_MODEL_DEFAULT") or DEFAULT_MODEL)


def normalize_class(name):
    """Canonical admission class for ``name`` (None -> ``standard``).
    Unknown classes raise ``ValueError`` — a typo'd class silently
    landing in best-effort would be an invisible demotion."""
    if name is None:
        return "standard"
    cls = str(name).strip().lower().replace("_", "-")
    if cls not in TENANT_CLASSES:
        raise ValueError(
            f"unknown tenant class {name!r} (expected one of "
            f"{', '.join(TENANT_CLASSES)})")
    return cls


def parse_class_map(spec, vtype=float):
    """Parse ``"priority:4,standard:2,best-effort:1"`` into a
    ``{class: value}`` dict (classes validated, values ``vtype``-cast).
    Empty/None -> ``{}``. The one parser behind every per-class knob
    (WFQ weights, depth shares, deadlines, loadgen ``--tenants``)."""
    out = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise ValueError(f"bad class spec entry {part!r} "
                             f"(expected class:value)")
        cls, _, val = part.partition(":")
        out[normalize_class(cls)] = vtype(val)
    return out


def class_weights():
    """Effective WFQ weights: defaults overlaid with
    ``MXNET_TPU_TENANT_WEIGHTS``. Weights must be positive."""
    w = dict(DEFAULT_CLASS_WEIGHTS)
    w.update(parse_class_map(envvars.get("MXNET_TPU_TENANT_WEIGHTS")))
    for cls, val in w.items():
        if val <= 0:
            raise ValueError(f"tenant class weight {cls}:{val} must "
                             f"be > 0")
    return w


def class_depth_shares():
    """Per-class depth budgets as fractions of the queue's
    ``max_depth`` (default 1.0 — no extra cap — so a single-class
    workload keeps the exact pre-tenancy admission behavior)."""
    shares = {cls: 1.0 for cls in TENANT_CLASSES}
    shares.update(
        parse_class_map(envvars.get("MXNET_TPU_TENANT_DEPTH_SHARES")))
    for cls, val in shares.items():
        if not 0.0 < val <= 1.0:
            raise ValueError(f"tenant depth share {cls}:{val} outside "
                             f"(0, 1]")
    return shares


def class_deadline_ms():
    """Per-class DEFAULT deadlines (ms) applied to requests that bring
    none of their own (``MXNET_TPU_TENANT_DEADLINE_MS``; empty = no
    class defaults)."""
    return parse_class_map(envvars.get("MXNET_TPU_TENANT_DEADLINE_MS"))


def class_slo_ms():
    """Per-class total-latency SLO thresholds (ms) for
    ``default_tenant_objectives`` (``MXNET_TPU_TENANT_SLO_MS``)."""
    return parse_class_map(envvars.get("MXNET_TPU_TENANT_SLO_MS"))


class UnknownModelError(LookupError):
    """Submit names a ``model_id`` this engine/registry does not
    host. (A LookupError, not a ServingError subclass, so the model
    axis stays importable below ``queue.py``; the engine re-raises it
    through the normal shed taxonomy.)"""


class ModelRegistry:
    """Thread-safe ``model_id -> (entry-point fn, version)`` map.

    The registry is the hot-swap pivot: ``resolve()`` is a dict read
    under a lock, ``swap()`` replaces the fn/version in one critical
    section — a dispatching worker sees either the old or the new
    model, never a half-swapped one. In-flight batches keep the fn
    they resolved; the queue is untouched, so a swap loses nothing.
    """

    def __init__(self, models=None, default=None):
        self._lock = threading.Lock()
        self._entries = {}          # model_id -> {"fn", "version"}
        self._default = None
        for mid, fn in (models or {}).items():
            self.register(mid, fn)
        if default is not None:
            self._default = str(default)

    @classmethod
    def of(cls, model, model_id=None):
        """Wrap a plain entry-point callable into a one-model registry
        (or pass an existing registry through) — how ``ServingEngine``
        keeps its ``model`` argument backward compatible."""
        if isinstance(model, ModelRegistry):
            return model
        reg = cls()
        reg.register(model_id or default_model_id(), model)
        return reg

    def register(self, model_id, fn, version=None):
        if not callable(fn):
            raise TypeError(f"model {model_id!r} entry point is not "
                            f"callable: {fn!r}")
        mid = str(model_id)
        with self._lock:
            self._entries[mid] = {"fn": fn,
                                  "version": str(version or "v0")}
            if self._default is None:
                self._default = mid
        return mid

    def resolve_id(self, model_id=None):
        """Canonical hosted id for ``model_id`` (None -> the default
        model); raises :class:`UnknownModelError` otherwise."""
        with self._lock:
            mid = str(model_id) if model_id is not None else self._default
            if mid is None or mid not in self._entries:
                raise UnknownModelError(
                    f"model {model_id!r} not hosted here (have: "
                    f"{sorted(self._entries) or 'none'})")
            return mid

    def resolve(self, model_id=None):
        """``(model_id, fn)`` for dispatch."""
        with self._lock:
            mid = str(model_id) if model_id is not None else self._default
            entry = self._entries.get(mid) if mid is not None else None
            if entry is None:
                raise UnknownModelError(
                    f"model {model_id!r} not hosted here (have: "
                    f"{sorted(self._entries) or 'none'})")
            return mid, entry["fn"]

    def swap(self, model_id, fn, version=None):
        """Atomically cut ``model_id`` over to ``fn``/``version``;
        returns the previous version string. The caller (the engine)
        warm-replays the model's visited shapes through ``fn`` BEFORE
        calling this, so post-swap traffic is warm."""
        if not callable(fn):
            raise TypeError(f"model {model_id!r} entry point is not "
                            f"callable: {fn!r}")
        mid = str(model_id)
        with self._lock:
            entry = self._entries.get(mid)
            if entry is None:
                raise UnknownModelError(
                    f"cannot swap unknown model {mid!r}")
            old = entry["version"]
            self._entries[mid] = {"fn": fn,
                                  "version": str(version or old)}
        return old

    def ids(self):
        with self._lock:
            return sorted(self._entries)

    def default_id(self):
        with self._lock:
            return self._default

    def versions(self):
        """``{model_id: version}`` — advertised at ``/healthz`` so the
        router's canary targets re-TOFU on hot-swap."""
        with self._lock:
            return {mid: e["version"]
                    for mid, e in sorted(self._entries.items())}


class TenantStats:
    """Per-engine tenant/model observability slice.

    Registry families (all four labels — the mxlint
    ``metric-tenant-label`` contract for ``mxnet_tpu_serving_tenant_*``
    names):

    - ``..._tenant_requests_total``   — admission/completion outcomes
      per tenant/model (``shed`` = WFQ eviction under overload);
    - ``..._tenant_latency_ms``       — total request latency
      histogram, the family ``default_tenant_objectives`` judges with
      per-class ``match=`` filters (label subset matching);
    - ``..._tenant_cost_seconds_total`` / ``..._tenant_tokens_total``
      — the billing axis: amortized device seconds and valid tokens.

    ``bills()`` derives ``device_s_per_1k_tokens`` per tenant (and per
    model within it) — the engine's side of the loadgen cost
    cross-check.
    """

    def __init__(self, engine_id, registry=None):
        reg = registry if registry is not None else REGISTRY
        self.engine_id = str(engine_id)
        self._lock = threading.Lock()
        self._rows = {}             # (tenant, tclass, model) -> row
        self._req = reg.counter(
            "mxnet_tpu_serving_tenant_requests_total",
            "serving requests by tenant, admission class, model and "
            "outcome (shed = WFQ overload eviction), per engine",
            ("engine_id", "tenant", "tenant_class", "model", "event"))
        self._lat = reg.histogram(
            "mxnet_tpu_serving_tenant_latency_ms",
            "total request latency by tenant, admission class and "
            "model, per engine (the per-class SLO family)",
            ("engine_id", "tenant", "tenant_class", "model"))
        self._sec = reg.counter(
            "mxnet_tpu_serving_tenant_cost_seconds_total",
            "amortized device seconds billed by tenant, admission "
            "class and model, per engine",
            ("engine_id", "tenant", "tenant_class", "model"))
        self._tok = reg.counter(
            "mxnet_tpu_serving_tenant_tokens_total",
            "valid tokens billed by tenant, admission class and "
            "model, per engine",
            ("engine_id", "tenant", "tenant_class", "model"))

    def _row(self, tenant, tclass, model):
        key = (tenant, tclass, model)
        row = self._rows.get(key)
        if row is None:
            row = self._rows.setdefault(
                key, {"events": {}, "device_s": 0.0, "tokens": 0})
        return row

    def observe_event(self, tenant, tclass, model, event, n=1):
        tenant = str(tenant or "anonymous")
        with self._lock:
            ev = self._row(tenant, tclass, model)["events"]
            ev[event] = ev.get(event, 0) + n
        self._req.labels(engine_id=self.engine_id, tenant=tenant,
                         tenant_class=tclass, model=model,
                         event=event).inc(n)

    def observe_latency(self, tenant, tclass, model, total_ms):
        tenant = str(tenant or "anonymous")
        self._lat.labels(engine_id=self.engine_id, tenant=tenant,
                         tenant_class=tclass,
                         model=model).observe(float(total_ms))

    def observe_cost(self, tenant, tclass, model, device_s, tokens):
        tenant = str(tenant or "anonymous")
        with self._lock:
            row = self._row(tenant, tclass, model)
            row["device_s"] += float(device_s)
            row["tokens"] += int(tokens)
        if device_s:
            self._sec.labels(engine_id=self.engine_id, tenant=tenant,
                             tenant_class=tclass,
                             model=model).inc(float(device_s))
        if tokens:
            self._tok.labels(engine_id=self.engine_id, tenant=tenant,
                             tenant_class=tclass,
                             model=model).inc(int(tokens))

    @staticmethod
    def _derive(row):
        out = {"events": dict(row["events"]),
               "device_s": round(row["device_s"], 6),
               "tokens": row["tokens"]}
        if row["tokens"]:
            out["device_s_per_1k_tokens"] = round(
                row["device_s"] * 1e3 / row["tokens"], 6)
        return out

    def bills(self):
        """``{tenant: {class, totals, by_model: {model: row}}}`` with
        derived per-1k-token rates — the ``/stats`` `tenants` block
        and ``telemetry_dump --fleet``'s per-tenant table."""
        with self._lock:
            items = [((t, c, m), {"events": dict(r["events"]),
                                  "device_s": r["device_s"],
                                  "tokens": r["tokens"]})
                     for (t, c, m), r in sorted(self._rows.items())]
        out = {}
        for (tenant, tclass, model), row in items:
            slot = out.setdefault(
                tenant, {"tenant_class": tclass, "by_model": {},
                         "device_s": 0.0, "tokens": 0, "events": {}})
            slot["tenant_class"] = tclass
            slot["by_model"][model] = self._derive(row)
            slot["device_s"] = round(slot["device_s"] + row["device_s"],
                                     6)
            slot["tokens"] += row["tokens"]
            for ev, n in row["events"].items():
                slot["events"][ev] = slot["events"].get(ev, 0) + n
        for slot in out.values():
            if slot["tokens"]:
                slot["device_s_per_1k_tokens"] = round(
                    slot["device_s"] * 1e3 / slot["tokens"], 6)
        return out


def wfq_depth_gauge(registry=None):
    """The per-class queue-depth pull gauge family (class-keyed, so
    deliberately OUTSIDE the tenant_* label contract — see module
    docstring)."""
    reg = registry if registry is not None else REGISTRY
    return reg.gauge(
        "mxnet_tpu_serving_wfq_queue_depth",
        "admission-queue depth by WFQ class, per engine",
        ("engine_id", "tenant_class"))
