"""In-process continuous-batching model server for encoder models.

``ServingEngine`` owns three pieces: a bounded :class:`RequestQueue`
(admission control), a :class:`ContinuousBatcher` (first-fit packing
into a closed set of shapes), and one worker thread running the
model's hybridized/CachedOp forward per packed batch — the in-process
analog of MXNet Model Server's queue → batcher → backend-worker
pipeline, with iteration-level (Orca-style) scheduling: every batch is
re-formed from whatever is queued the moment the previous batch
finishes, so a long request never convoys short ones behind it.

The model contract is one callable::

    model(ids, token_types, valid_length, segment_ids, positions)
      -> (B, S, U) NDArray            # or a tuple whose [0] is that

with every input an int32 NDArray in the io/packing.py layout
(``gluon.model_zoo.bert.bert_serving_entry`` adapts a BERTModel).
Because inputs arrive in a small closed shape set, the CachedOp
compile cache holds one executable per (rows, row_len) bucket and
steady-state serving never re-traces.
"""
from __future__ import annotations

import itertools
import os
import threading
import time

import numpy as np

from .. import autograd, compile_cache, envvars, profiler
from .. import ndarray as nd
from ..context import current_context
from ..telemetry import attribution as _attribution
from ..telemetry import events as _events
from ..telemetry import incidents as _incidents
from ..telemetry import profiling as _profiling
from ..telemetry import recorder as _recorder
from ..telemetry import spans as _spans
from ..telemetry.registry import REGISTRY as _REGISTRY
from ..telemetry.trace import trace_context as _trace_context
from . import tenancy
from .batcher import ContinuousBatcher
from .metrics import (CostLedger, ServingStats, exemplar_gate,
                      slow_exemplar)
from .queue import (DeadlineExceededError, EngineStoppedError,
                    QueueFullError, Request, RequestQueue,
                    RequestTooLongError, ServingError,
                    UnknownModelError)

__all__ = ["ServingEngine"]

_engine_seq = itertools.count()

# HTTP status for each admission/serving failure the /submit dispatch
# endpoint can report (the router maps error_type back to the class)
_SUBMIT_ERROR_STATUS = {
    "QueueFullError": 429,
    "RequestTooLongError": 413,
    "DeadlineExceededError": 504,
    # the handler's own fut.result(timeout) expiring is request-scoped
    # like a deadline: 504 tells a failover client NOT to replay it as
    # new work while this process may still be executing it
    "TimeoutError": 504,
    "EngineStoppedError": 503,
    # out-of-range sampling params are a malformed request, refused at
    # admission — before the compiled step could turn them into NaNs
    "InvalidSamplingError": 400,
    # the named model is not hosted by this engine — the multi-model
    # fleet's 404 (a router retries another seat; a client fixes its
    # model id)
    "UnknownModelError": 404,
}


def _join_trace_ids(requests, cap=16):
    """One contextvar value for a whole batch: the member requests'
    trace ids, comma-joined (capped — a 128-request batch must not
    grow a kilobyte span annotation). None when the batch is empty
    (warmup dummy forwards)."""
    ids = [r.trace_id for r in requests]
    if not ids:
        return None
    if len(ids) > cap:
        ids = ids[:cap] + [f"+{len(ids) - cap}more"]
    return ",".join(ids)


def _slice_tokens(seq_slice, request):
    """Default postprocess: the request's per-token outputs."""
    return seq_slice


def _mean_pool(seq_slice, request):
    return seq_slice.mean(axis=0)


def _cls_pool(seq_slice, request):
    return seq_slice[0]


_POOLERS = {"tokens": _slice_tokens, "mean": _mean_pool, "cls": _cls_pool}


class ServingEngine:
    """Continuous-batching server around one encoder forward.

    Parameters
    ----------
    model : callable or :class:`~.tenancy.ModelRegistry`
        The packed forward (see module docstring), or a registry of
        several — a multi-model engine dispatches each batch through
        the model its requests named (``submit(model_id=...)``), and
        ``swap_model`` hot-swaps any entry live.
    bucket_lens : row-length buckets (ascending); a request longer
        than the last one is rejected at submit.
    max_rows : packed rows per dispatched batch (row counts are
        quantized to powers of two up to this).
    max_queue_depth : admission bound; a full queue sheds with
        :class:`QueueFullError`.
    default_deadline_ms : deadline applied to requests that don't
        bring their own (None = no deadline).
    batch_wait_ms : linger after the first drained request to let a
        batch fill (0 = pure continuous batching; the queue already
        self-clocks under load because requests pile up while the
        previous batch computes).
    pool : per-request output view — "tokens" (len, U), "mean" (U,),
        "cls" (U,), or a callable ``(seq_slice, request) -> result``.
    engine_id : label value for this engine's serving metric families
        (and the ``engine`` attr on its spans). Defaults to a
        process-unique id; give stable names ("chip0") when a router
        fronts several engines so dashboards and the fleet scoreboard
        agree on who is who.
    """

    def __init__(self, model, ctx=None, bucket_lens=(64, 256, 1024),
                 max_rows=8, max_queue_depth=256, default_deadline_ms=None,
                 batch_wait_ms=0.0, max_batch_requests=None, pool="tokens",
                 pad_value=0, stats_window=4096, engine_id=None):
        # model identity: a plain callable becomes a one-entry
        # registry under the default model id — the pre-tenancy API
        # unchanged. Dispatch resolves the fn through the registry per
        # batch, so a hot-swap (or a chaos wrap via the _model
        # property) takes effect at the next batch boundary.
        self._models = tenancy.ModelRegistry.of(model)
        self.engine_id = str(engine_id) if engine_id is not None \
            else f"e{os.getpid():x}-{next(_engine_seq)}"
        self._ctx = ctx if ctx is not None else current_context()
        self._batcher = ContinuousBatcher(bucket_lens=bucket_lens,
                                          max_rows=max_rows,
                                          pad_value=pad_value)
        self._queue = RequestQueue(max_queue_depth)
        self._default_deadline_ms = default_deadline_ms
        self._batch_wait_s = batch_wait_ms / 1e3
        # a packed batch holds at most rows*row_len/1 requests; the
        # drain cap just bounds per-iteration work
        self._max_batch_requests = (max_batch_requests
                                    or max_rows * self._batcher.max_len)
        self._pool = _POOLERS[pool] if isinstance(pool, str) else pool
        self.stats = ServingStats(stats_window, engine_id=self.engine_id)
        self.stats.set_queue_depth_fn(lambda: len(self._queue))
        # per-tenant/per-model observability slice + the per-class WFQ
        # depth pull gauges (scrape-time reads, zero hot-path cost)
        self.tenants = tenancy.TenantStats(self.engine_id)
        wfq = tenancy.wfq_depth_gauge()
        for cls in tenancy.TENANT_CLASSES:
            wfq.labels(engine_id=self.engine_id, tenant_class=cls) \
               .set_function(
                   lambda c=cls: self._queue.depths().get(c, 0))
        # per-bucket cost ledger: device/compile seconds + requests +
        # tokens, cumulative for the process lifetime (reset_stats
        # swaps the stats WINDOW, never the ledger — /costs scrapers
        # diff, same contract as registry counters)
        self.costs = CostLedger(self.engine_id)
        cc = _REGISTRY.counter(
            "mxnet_tpu_serving_compile_cache_total",
            "per-shape executable cache outcomes at dispatch: "
            "memory_hit (in-process), persistent_hit (on-disk cache "
            "served the compile), miss (fresh backend compile)",
            ("engine_id", "result"))
        self._compile_cache = {
            r: cc.labels(engine_id=self.engine_id, result=r)
            for r in ("memory_hit", "persistent_hit", "miss")}
        self._cc_counts = {r: 0 for r in self._compile_cache}
        # visited shape buckets, keyed (model_id, rows, row_len): each
        # hosted model owns its compile universe; the exported warmup
        # manifest stays the plain (rows, row_len) union
        self._seen_shapes = set()
        # guards _seen_shapes + the compile-cache tallies: the worker
        # dispatches while warmup()/warmup_manifest() run on caller
        # threads and the router's poll thread reads the manifest
        self._shapes_lock = threading.Lock()
        # monotonic stamp while a first-visit trace+compile is in
        # flight — the watchdog widens its stall threshold over this
        # window so legitimate compiles never trip a flight bundle
        self._compiling_since = None
        # serializes model forwards across threads: the worker
        # dispatches live batches while warmup() replays shapes on the
        # caller's thread (and black-box canaries make day-one traffic
        # during warmup the NORMAL case, not a misuse) — the CachedOp
        # build path must never trace one block from two threads at
        # once (UnexpectedTracerError). Uncontended cost per batch is
        # one lock op; a compile legitimately holds it for seconds
        # while a waiter queues, hence the long-hold allowance.
        self._forward_lock = threading.Lock()  # mxsan: allow=long-hold
        # SLO engine (MXNET_TPU_SLO): declarative objectives over this
        # engine's metric families + the alert daemon judging them —
        # built in start(), exposed at /slo + /alerts
        self._slo = None
        # history scraper (MXNET_TPU_HISTORY): the retrospective
        # time-series store behind /query_range — built in start()
        self._history = None
        # traffic capture (MXNET_TPU_CAPTURE): the sampled request
        # corpus behind /capture and deterministic replay — built in
        # start(); None means no record branch in _dispatch at all
        self._capture = None
        # exemplar gate, resolved once; the exemplar↔retrievable-trace
        # contract lives in metrics.slow_exemplar (shared with router)
        self._exemplars = exemplar_gate()
        self._worker = None
        self._expo = None
        self._wire = None           # binary dispatch listener (expose)
        self._abort = False
        self._started = False
        self._lock = threading.Lock()
        # watchdog surface: the worker loop beats every iteration, so
        # a beat that stops while running means a wedged forward (or a
        # deadlocked drain) — exactly what the stall probe reports
        self._beat = time.monotonic()
        self._last_dispatch = self._beat
        self._probe_name = f"serving_engine_{id(self):x}"

    @property
    def _model(self):
        """The DEFAULT model's entry point — the pre-registry
        attribute the chaos harness wraps/unwraps in place."""
        return self._models.resolve(None)[1]

    @_model.setter
    def _model(self, fn):
        # in-place fn replacement keeps the version: chaos wraps must
        # not look like a new model version (no canary re-TOFU)
        self._models.swap(self._models.default_id(), fn)

    @property
    def models(self):
        """The engine's :class:`~.tenancy.ModelRegistry`."""
        return self._models

    # -- lifecycle ---------------------------------------------------------
    def start(self):
        with self._lock:
            if self._started:
                return self
            if self._queue.closed:
                raise EngineStoppedError("engine cannot be restarted")
            self._started = True
            self._beat = time.monotonic()
            self._last_dispatch = self._beat
            self._worker = threading.Thread(target=self._run,
                                            name="mxnet_tpu_serving",
                                            daemon=True)
            self._worker.start()
        # serving compiles should outlive this process: point the
        # persistent compilation cache at disk before the first trace
        compile_cache.ensure()
        # a serving process should be able to explain its own death:
        # flight-recorder crash hooks + the stall watchdog ride along
        _recorder.install()
        _recorder.register_probe(self._probe_name, self._watchdog_probe)
        # flight bundles carry the scheduler's WFQ view: per-class
        # queue split + hosted model versions at crash time
        self._bundle_name = f"engine_scheduler_{self.engine_id}"
        _recorder.add_bundle_section(self._bundle_name,
                                     self.scheduler_state)
        # ... and narrate it: the incident tracker folds alert
        # firings, watchdog trips and scoreboard transitions into the
        # /incidents timeline (thread-free — an events tap)
        _incidents.install()
        # ... and where its host time goes while alive: the always-on
        # sampling profiler + resource sweep (MXNET_TPU_PROF=0 opts out)
        _profiling.ensure_started()
        # ... and judge its own health: the SLO engine declares the
        # default serving objectives (latency quantile, availability,
        # optional cost budget) and the alert daemon walks the SRE
        # multi-window burn-rate rules over them (MXNET_TPU_SLO=0
        # opts out of evaluation, exemplars and the endpoints)
        if envvars.get("MXNET_TPU_SLO"):
            from ..telemetry.alerts import (AlertDaemon, default_burn_rules,
                                            default_serving_objectives,
                                            default_tenant_objectives)
            from ..telemetry.slo import SloEvaluator
            evaluator = SloEvaluator(self.engine_id)
            names = default_serving_objectives(evaluator, self.engine_id)
            names += default_tenant_objectives(evaluator, self.engine_id)
            self._slo = AlertDaemon(evaluator)
            default_burn_rules(self._slo, names)
            self._slo.start()
        # ... and remember: the history scraper samples this process's
        # registry into the retrospective store — /query_range,
        # incident forensics and retro SLO replay all read it
        # (MXNET_TPU_HISTORY=0: no thread, no store)
        if envvars.get("MXNET_TPU_HISTORY"):
            from ..telemetry.history import HistoryScraper
            self._history = HistoryScraper(
                self.engine_id,
                slo_fn=(self.slo_snapshot if self._slo is not None
                        else None),
                alerts_fn=(self.alerts_snapshot
                           if self._slo is not None else None)).start()
        # ... and keep the receipts: sampled traffic capture records a
        # head-sampled fraction of admitted requests into the bounded
        # corpus deterministic replay re-executes (MXNET_TPU_CAPTURE=0:
        # one env read — no thread, no families, no files)
        if envvars.get("MXNET_TPU_CAPTURE"):
            from .capture import CaptureStore
            self._capture = CaptureStore(self.engine_id)
        # chaos harness (MXNET_TPU_CHAOS): register as a fault target.
        # Off (the default) this is ONE env read — nothing is built,
        # patched or spawned.
        if envvars.get("MXNET_TPU_CHAOS"):
            from .chaos import register_engine as _chaos_register
            _chaos_register(self)
        _events.emit("engine_start", engine_id=self.engine_id,
                     bucket_lens=list(self._batcher.bucket_lens),
                     max_rows=self._batcher.max_rows)
        return self

    def stop(self, drain=True, timeout=None):
        """Shut down. ``drain=True`` finishes every queued/in-flight
        request first; ``drain=False`` fails them with
        :class:`EngineStoppedError` (counted ``cancelled``)."""
        _events.emit("engine_abort" if not drain else "engine_stop",
                     engine_id=self.engine_id, drain=drain)
        _recorder.unregister_probe(self._probe_name)
        _recorder.remove_bundle_section(
            getattr(self, "_bundle_name", f"engine_scheduler_"
                                          f"{self.engine_id}"))
        if self._slo is not None:
            self._slo.stop()
        if self._history is not None:
            self._history.stop()
        if self._capture is not None:
            self._capture.close()
        with self._lock:
            self._queue.close()
            if not drain:
                self._abort = True
            worker = self._worker
        timed_out = False
        if worker is not None:
            worker.join(timeout)
            timed_out = worker.is_alive()
        # requests still queued after the worker exited (stop before
        # start, abort racing new submits, or a HUNG worker — a stuck
        # forward will never serve them) fail loudly; the exposition
        # server closes either way so the port never leaks
        for r in self._queue.drain_all():
            self.stats.bump("cancelled")
            r.span.end(error="cancelled: engine stopped")
            r.future.set_exception(
                EngineStoppedError("engine stopped before request ran"))
        # release the registry's queue-depth closure (it would pin this
        # engine — params, compile caches — for the process lifetime
        # and report a dead queue as live) and the exposition server;
        # swap under the lock so a racing expose() can't leak one. The
        # queue was just drained, so a constant 0 stays truthful.
        self.stats.set_queue_depth_fn(lambda: 0)
        with self._lock:
            expo, self._expo = self._expo, None
            wire, self._wire = self._wire, None
        if wire is not None:
            wire.close()
        if expo is not None:
            expo.close()
        if timed_out:
            raise ServingError("serving worker did not stop in time")

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop(drain=True)
        return False

    @property
    def running(self):
        with self._lock:
            return (self._started and self._worker is not None
                    and self._worker.is_alive())

    # -- client surface ----------------------------------------------------
    def submit(self, tokens, token_types=None, deadline_ms=None,
               trace_id=None, parent_span_id=None, model_id=None,
               tenant=None, tenant_class=None):
        """Enqueue one request; returns an :class:`InferenceFuture`.
        Raises the admission errors directly (queue full, too long,
        stopped, unknown model) so callers can tell shedding from
        failure.

        ``model_id`` names the hosted model to run (None = the
        default); ``tenant``/``tenant_class`` attribute the request to
        an owner and its WFQ admission class (None = ``standard``).

        ``trace_id``/``parent_span_id`` adopt an upstream trace (the
        router's dispatch, or a remote ``/submit`` payload): the
        request joins that trace and its ``serving/request`` span
        parents under the given — possibly remote — span id."""
        if deadline_ms is None:
            deadline_ms = self._default_deadline_ms
        # validate FIRST: a malformed request (empty tokens, mismatched
        # token_types, unknown class) raises to the caller without
        # touching any counter, so submitted always equals the sum of
        # the outcome counters (the invariant the loadgen cross-check
        # reconciles)
        req = Request(tokens, token_types, deadline_ms,
                      trace_id=trace_id, parent_span_id=parent_span_id,
                      tenant=tenant, tenant_class=tenant_class,
                      model_id=model_id)
        req.span.set_attr(engine=self.engine_id)
        self.stats.bump("submitted")
        try:
            # canonicalize up front: dispatch and billing then never
            # re-resolve, and an unknown model is a typed 404 here
            req.model_id = self._models.resolve_id(req.model_id)
        except UnknownModelError:
            self.stats.bump("rejected_unknown_model")
            self.tenants.observe_event(
                req.tenant, req.tenant_class, str(model_id),
                "rejected_unknown_model")
            _events.emit("request_shed", reason="unknown_model",
                         engine_id=self.engine_id, model=str(model_id),
                         trace_id=req.trace_id, tokens=len(req))
            req.span.set_attr(shed="unknown_model").force_keep() \
               .end(error="shed: unknown_model")
            raise
        self.tenants.observe_event(req.tenant, req.tenant_class,
                                   req.model_id, "submitted")
        if not self._started or self._queue.closed:
            self.stats.bump("rejected_stopped")
            req.span.end(error="rejected: engine not running")
            raise EngineStoppedError("serving engine is not running")
        if len(req) > self._batcher.max_len:
            self.stats.bump("rejected_too_long")
            _events.emit("request_shed", reason="too_long",
                         engine_id=self.engine_id,
                         trace_id=req.trace_id, tokens=len(req))
            req.span.set_attr(shed="too_long").force_keep() \
               .end(error="shed: too_long")
            raise RequestTooLongError(
                f"request of {len(req)} tokens exceeds the largest row "
                f"bucket ({self._batcher.max_len})")
        try:
            victim = self._queue.put(req)
        except ServingError as e:
            full = not self._queue.closed
            reason = "queue_full" if full else "stopped"
            self.stats.bump("rejected_queue_full"
                            if full else "rejected_stopped")
            self.tenants.observe_event(
                req.tenant, req.tenant_class, req.model_id,
                "rejected_queue_full" if full else "rejected_stopped")
            _events.emit("request_shed", reason=reason,
                         engine_id=self.engine_id,
                         trace_id=req.trace_id, tokens=len(req))
            # shed traces are tail-sampling KEEPs by contract: the
            # operator debugging overload wants exactly these
            req.span.set_attr(shed=reason).force_keep() \
               .end(error=f"shed: {reason}")
            raise e
        if victim is not None:
            self._shed_victim(victim)
        return req.future

    def _shed_victim(self, victim):
        """Fail a request the WFQ queue EVICTED to admit a
        higher-class arrival under overload — best-effort sheds
        first, priority last, and the shed is loud on every surface
        (counter, tenant slice, event, kept trace)."""
        self.stats.bump("rejected_queue_full")
        self.tenants.observe_event(victim.tenant, victim.tenant_class,
                                   victim.model_id
                                   or self._models.default_id(),
                                   "shed")
        _events.emit("request_shed", reason="wfq_evicted",
                     engine_id=self.engine_id,
                     trace_id=victim.trace_id,
                     tenant_class=victim.tenant_class,
                     tokens=len(victim))
        victim.span.set_attr(shed="wfq_evicted").force_keep() \
              .end(error="shed: wfq_evicted")
        victim.future.set_exception(QueueFullError(
            f"shed by weighted-fair admission: queue full and a "
            f"higher class arrived (class {victim.tenant_class})"))

    def infer(self, tokens, token_types=None, deadline_ms=None,
              timeout=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(tokens, token_types, deadline_ms).result(timeout)

    def warmup(self, shapes=None, manifest=None, model_id=None):
        """Compile ahead of traffic: run one dummy forward per
        (rows, row_len) shape the batcher can emit (or the given
        subset). Serving latency then never pays a trace+compile.

        ``manifest`` (a dict from :func:`~mxnet_tpu.compile_cache.
        load_manifest` / a router's ``/warmup``, or a path to one)
        replays exactly the fleet's VISITED buckets instead of the
        whole universe — the warm-restart path: with the persistent
        compilation cache primed, each replay is a disk fetch, and
        the first real request after a rolling restart runs warm.
        Manifest shapes outside this batcher's universe are skipped
        (a config drift degrades coverage, never crashes startup).

        Call BEFORE submitting traffic (right after ``start``): the
        dummy forwards run on the caller's thread, and tracing the
        same block from two threads at once (warmup racing a live
        batch) is not supported by the CachedOp build path."""
        if manifest is not None:
            if isinstance(manifest, (str, os.PathLike)):
                manifest = compile_cache.load_manifest(manifest)
            universe = set(self._batcher.shape_universe())
            want = compile_cache.manifest_shapes(manifest)
            shapes = [s for s in want if s in universe]
            _events.emit("warmup_replay", engine_id=self.engine_id,
                         shapes=len(shapes),
                         skipped_incompatible=len(want) - len(shapes))
        if shapes is None:
            shapes = self._batcher.shape_universe()
        for rows, row_len in shapes:
            self._forward_shape(rows, row_len, model_id=model_id)
        return self

    def warmup_manifest(self):
        """This engine's visited-shape warmup manifest (exported at
        ``/warmup`` by :meth:`expose`; the fronting router unions the
        fleet's and persists it for restarts). Shapes are the plain
        (rows, row_len) union across hosted models — the manifest
        format predates the model axis and a replay re-warms every
        registered model through :meth:`warmup` anyway."""
        with self._shapes_lock:
            shapes = sorted({(r, l) for _m, r, l in self._seen_shapes})
        return compile_cache.new_manifest(
            self.engine_id, self._batcher.bucket_lens,
            self._batcher.max_rows, shapes)

    def swap_model(self, model, model_id=None, version=None,
                   shapes=None, gate=None):
        """Live hot-swap: cut ``model_id`` (None = the default model)
        over to the new ``model`` entry point with ZERO lost requests.

        The new fn is first warm-replayed over the model's visited
        shape buckets (or the explicit ``shapes``) on the caller's
        thread — each replay traces+compiles the new version's
        executables under the forward lock, exactly like ``warmup`` —
        and only then does the registry flip atomically. Queued and
        in-flight requests are untouched: a batch dispatched before
        the flip finishes on the old fn, the next batch resolves the
        new one, and post-swap traffic runs warm. The version change
        is advertised at ``/healthz``, so a fronting router's canary
        targets change token and the canary re-TOFUs its golden.

        ``gate`` (optional) is consulted BEFORE any warm-replay work:
        a :class:`~.shadow.ShadowMirror` (its shadow-diff verdict
        decides), or any callable returning ``(ok, reason)``. A
        failing gate raises :class:`~.shadow.SwapGateError` and the
        live model keeps serving — evidence first, flip second."""
        if gate is not None:
            gate_fn = getattr(gate, "gate", None) or gate
            ok, reason = gate_fn()
            if not ok:
                from .shadow import SwapGateError
                _events.emit("model_swap_refused",
                             engine_id=self.engine_id,
                             model=str(model_id), version=version,
                             reason=reason)
                raise SwapGateError(
                    f"swap_model refused by gate: {reason}")
        mid = self._models.resolve_id(model_id)
        if shapes is None:
            with self._shapes_lock:
                shapes = sorted((r, l) for m, r, l in self._seen_shapes
                                if m == mid)
        _events.emit("model_swap_begin", engine_id=self.engine_id,
                     model=mid, version=version, shapes=len(shapes))
        t0 = time.monotonic()
        for rows, row_len in shapes:
            self._forward_shape(rows, row_len, fn=model)
        old = self._models.swap(mid, model, version)
        _events.emit("model_swap", engine_id=self.engine_id, model=mid,
                     from_version=old,
                     to_version=self._models.versions().get(mid),
                     warmed_shapes=len(shapes),
                     ms=round((time.monotonic() - t0) * 1e3, 3))
        return self

    @property
    def capture(self):
        """The engine's :class:`~.capture.CaptureStore` (None unless
        ``MXNET_TPU_CAPTURE`` was on at start)."""
        return self._capture

    def capture_summary(self):
        """The ``/capture`` body (None when capture is disabled) —
        what a fronting router's fleet merge reads per seat."""
        return (self._capture.summary()
                if self._capture is not None else None)

    def reset_stats(self):
        """Swap in a fresh ServingStats (compile cache untouched):
        separates a warmup/throwaway traffic window from the measured
        one — lifetime-cumulative stats would otherwise fold both.
        The process-wide telemetry registry keeps counting (Prometheus
        counters never reset); scrapers diff between scrapes."""
        self.stats = ServingStats(self.stats.window,
                                  engine_id=self.engine_id)
        self.stats.set_queue_depth_fn(lambda: len(self._queue))
        return self

    def expose(self, port=0, host="127.0.0.1"):
        """Start (or return the running) telemetry exposition server
        for this engine: Prometheus ``/metrics`` off the process
        registry, ``/healthz`` liveness (worker thread alive, queue
        open, seconds since the worker loop's last beat), ``/stats``
        serving this engine's ``snapshot()`` JSON, ``/costs`` (the
        per-bucket cost ledger), ``/profile`` (the process continuous
        profiler's collapsed stacks), ``/slo`` + ``/alerts`` (the SLO
        engine's objective table and alert-rule state, present unless
        ``MXNET_TPU_SLO=0``), and ``POST
        /submit`` — the remote dispatch endpoint a
        :class:`~.router.ServingRouter` in another process drives
        (JSON request in, JSON result out, long-polled until the
        forward completes). ``port=0`` picks a free port (read
        ``.port`` back). Closed automatically by :meth:`stop`.

        Unless ``MXNET_TPU_WIRE=0``, a binary dispatch listener
        (:class:`~.wire.WireListener`) starts alongside and its port
        is advertised in ``/healthz`` — wire-capable routers upgrade
        their dispatch transport off that; JSON-only peers keep using
        ``POST /submit``."""
        from ..telemetry.expo import TelemetryServer

        with self._lock:
            if self._queue.closed:
                # stop() already ran (or is draining): a fresh server
                # here would have no one to close it
                raise EngineStoppedError(
                    "cannot expose telemetry on a stopped engine")
            if self._expo is not None:
                return self._expo

            def healthz():
                alive = (self._worker is not None
                         and self._worker.is_alive())
                closed = self._queue.closed
                compiling = self._compiling_since
                wire = self._wire
                return (alive and not closed,
                        {"engine_id": self.engine_id,
                         "worker_alive": alive, "queue_closed": closed,
                         "queue_depth": len(self._queue),
                         "compiling": compiling is not None,
                         "wire_port": (wire.port if wire is not None
                                       else None),
                         # hosted models + versions: the router's seat
                         # model filter AND the canary re-TOFU trigger
                         # (a version flip changes the target token)
                         "models": self._models.versions(),
                         "seconds_since_beat":
                             round(time.monotonic() - self._beat, 3)})

            srv = TelemetryServer(healthz_fn=healthz,
                                  stats_fn=self.snapshot,
                                  submit_fn=self._remote_submit,
                                  warmup_fn=self.warmup_manifest,
                                  costs_fn=self.cost_table,
                                  slo_fn=(self.slo_snapshot
                                          if self._slo is not None
                                          else None),
                                  alerts_fn=(self.alerts_snapshot
                                             if self._slo is not None
                                             else None),
                                  history_fn=(self._history.store
                                              if self._history is not None
                                              else None),
                                  whyslow_fn=self.whyslow,
                                  capture_fn=(self._capture.summary
                                              if self._capture is not None
                                              else None),
                                  port=port, host=host)
            self._expo = srv
            # the binary dispatch listener rides along with the HTTP
            # server (MXNET_TPU_WIRE=0 opts out): /healthz advertises
            # its port so a fronting router upgrades its transport —
            # a bind failure degrades to HTTP dispatch, never to a
            # dead engine
            if envvars.get("MXNET_TPU_WIRE") and self._wire is None:
                from .wire import WireListener
                try:
                    self._wire = WireListener(self, host=host)
                except OSError as e:
                    _events.emit("wire_listen_error",
                                 engine_id=self.engine_id,
                                 error=repr(e))
        # emit/return through the local: a stop() racing in right here
        # may already have swapped self._expo away (and closed it)
        _events.emit("telemetry_expose", engine_id=self.engine_id,
                     port=srv.port, host=srv.host)
        return srv

    def snapshot(self):
        """Stats dict: counters, queue depth, latency percentiles,
        packing efficiency (see metrics.ServingStats).
        ``seconds_since_beat`` is the worker loop's heartbeat age —
        the router's health poll reads it to tell a WEDGED engine
        (alive thread, stuck forward) from a healthy one."""
        out = self.stats.snapshot()
        out["running"] = self.running
        out["bucket_lens"] = list(self._batcher.bucket_lens)
        out["max_rows"] = self._batcher.max_rows
        out["seconds_since_beat"] = round(time.monotonic() - self._beat, 3)
        with self._shapes_lock:
            out["compile_cache"] = dict(self._cc_counts)
            out["manifest_shapes"] = len(self._seen_shapes)
        out["compiling"] = self._compiling_since is not None
        out["costs"] = self.costs.totals()
        out["models"] = self._models.versions()
        out["queue_classes"] = self._queue.depths()
        out["tenants"] = self.tenants.bills()
        return out

    @property
    def alerts(self):
        """The engine's :class:`~mxnet_tpu.telemetry.alerts.
        AlertDaemon` (None when ``MXNET_TPU_SLO=0`` or before
        ``start``) — tests and drills drive ``evaluate_once`` /
        declare extra rules through it."""
        return self._slo

    def slo_snapshot(self):
        """The ``/slo`` body: per declared objective the SLI (or
        windowed value), burn rates over the canonical windows, and
        error budget remaining over the budget window."""
        if self._slo is None:
            return {"owner": self.engine_id, "enabled": False,
                    "objectives": {}}
        return self._slo.evaluator.snapshot()

    def alerts_snapshot(self):
        """The ``/alerts`` body: every rule's state-machine position,
        evidence (burn history, latency exemplars) and the recent
        transition log."""
        if self._slo is None:
            return {"owner": self.engine_id, "enabled": False,
                    "rules": []}
        return self._slo.snapshot()

    def scheduler_state(self):
        """Flight-bundle scheduler section: the WFQ per-class queue
        split + hosted model versions — what was queued for whom when
        the process needed explaining."""
        return {"engine_id": self.engine_id,
                "queue_classes": self._queue.depths(),
                "queue_depth": len(self._queue),
                "models": self._models.versions()}

    def cost_table(self):
        """The ``/costs`` body: this engine's per-bucket cost ledger
        (device/compile seconds, requests, valid tokens, derived
        per-request and per-1k-token rates) plus the cross-bucket
        totals. A fronting router merges these into the fleet table."""
        return {"engine_id": self.engine_id,
                "buckets": self.costs.table(),
                "totals": self.costs.totals()}

    def whyslow(self):
        """The ``/whyslow`` body: per-stage attribution table + top
        stages by share of attributed time (empty, ``enabled:
        false``, when attribution is off — never a 404)."""
        agg = _attribution.get_aggregator(self.engine_id)
        if agg is None:
            return {"owner": self.engine_id,
                    "enabled": _attribution.enabled(),
                    "requests": 0, "stages": [], "top": []}
        return agg.snapshot()

    def _remote_submit(self, payload):
        """``POST /submit`` handler (runs on an exposition-server
        thread): submit + block for the result, JSON-serializable
        either way. Returns ``(http_status, body_dict)`` — admission
        errors carry their class name in ``error_type`` so the remote
        router re-raises the same serving taxonomy. ``engine_ms`` (the
        engine-observed submit→result wall) rides back so the router
        can split its dispatch round trip into engine time vs
        transport overhead — the wire-vs-JSON comparison axis."""
        t0 = time.perf_counter()
        try:
            fut = self.submit(payload["tokens"],
                              payload.get("token_types"),
                              deadline_ms=payload.get("deadline_ms"),
                              trace_id=payload.get("trace_id"),
                              parent_span_id=payload.get("span_id"),
                              model_id=payload.get("model_id"),
                              tenant=payload.get("tenant"),
                              tenant_class=payload.get("tenant_class"))
        except (ServingError, ValueError, LookupError, TypeError) as e:
            name = type(e).__name__
            return (_SUBMIT_ERROR_STATUS.get(name, 400),
                    {"ok": False, "error_type": name, "error": str(e),
                     "engine_id": self.engine_id})
        timeout_s = payload.get("timeout_s") or 600.0
        try:
            out = fut.result(timeout=float(timeout_s))
        except Exception as e:
            name = type(e).__name__
            return (_SUBMIT_ERROR_STATUS.get(name, 500),
                    {"ok": False, "error_type": name, "error": str(e),
                     "trace_id": fut.trace_id,
                     "engine_id": self.engine_id})
        return 200, {"ok": True, "result": np.asarray(out).tolist(),
                     "trace_id": fut.trace_id,
                     "engine_id": self.engine_id,
                     "engine_ms": round(
                         (time.perf_counter() - t0) * 1e3, 3),
                     # amortized cost attribution crosses the wire so
                     # a remote router's caller sees the same bill an
                     # in-process caller would
                     "cost": getattr(fut, "cost", None),
                     "breakdown": getattr(fut, "breakdown", None)}

    # -- watchdog ----------------------------------------------------------
    def _watchdog_probe(self):
        """None while healthy; an anomaly dict when the worker loop
        stopped beating (wedged forward) or the queue sits saturated
        with no dispatch progressing."""
        if not self.running:
            return None
        now = time.monotonic()
        stall = _recorder.stall_seconds()
        if self._compiling_since is not None:
            # a first-visit trace+compile window is open: widen the
            # threshold (ROADMAP carried follow-up) — tens-of-seconds
            # compiles are progress, not a stall, and must not burn
            # flight-recorder bundles; a compile outliving even the
            # grace still trips
            stall += envvars.get("MXNET_TPU_WATCHDOG_COMPILE_GRACE_S")
        since_beat = now - self._beat
        if since_beat > stall:
            return {"kind": "serving_worker_stall",
                    "seconds_since_beat": round(since_beat, 3),
                    "queue_depth": len(self._queue)}
        depth = len(self._queue)
        if (depth >= self._queue.max_depth
                and now - self._last_dispatch > stall):
            return {"kind": "serving_queue_saturated",
                    "queue_depth": depth,
                    "seconds_since_dispatch": round(
                        now - self._last_dispatch, 3)}
        return None

    # -- worker ------------------------------------------------------------
    def _run(self):
        carry = []
        while True:
            self._beat = time.monotonic()
            if self._abort:
                self._fail(carry, EngineStoppedError(
                    "engine stopped before request ran"), "cancelled")
                carry = []
                return
            drained = self._queue.poll(
                self._max_batch_requests - len(carry),
                timeout=0.0 if carry else 0.05)
            if drained and self._batch_wait_s > 0 \
                    and len(carry) + len(drained) < self._max_batch_requests:
                time.sleep(self._batch_wait_s)   # linger for the batch
                drained += self._queue.poll(
                    self._max_batch_requests - len(carry) - len(drained))
            reqs = carry + drained
            carry = []
            if not reqs:
                if self._queue.closed and not len(self._queue):
                    return                       # clean drain complete
                continue
            now = time.monotonic()
            live = []
            for r in reqs:
                if r.expired(now):
                    self.stats.bump("expired")
                    self.tenants.observe_event(
                        r.tenant, r.tenant_class,
                        r.model_id or self._models.default_id(),
                        "expired")
                    _events.emit("request_expired", trace_id=r.trace_id,
                                 waited_ms=round((now - r.t_submit) * 1e3,
                                                 3))
                    self._queue_span(r)
                    r.span.end(error="deadline exceeded before dispatch")
                    r.future.set_exception(DeadlineExceededError(
                        f"request {r.id} deadline exceeded before "
                        "dispatch"))
                else:
                    live.append(r)
            if not live:
                continue
            # one packed batch per MODEL, in first-arrival order: a
            # compiled executable exists per (model, shape), so a
            # batch never mixes models — the WFQ drain order above is
            # preserved within each group
            groups, index = [], {}
            for r in live:
                mid = r.model_id or self._models.default_id()
                if mid not in index:
                    index[mid] = len(groups)
                    groups.append((mid, []))
                groups[index[mid]][1].append(r)
            for mid, members in groups:
                try:
                    t0 = time.perf_counter()
                    with _trace_context(_join_trace_ids(members)):
                        with profiler.Scope("serving/pack"):
                            plan, leftover = self._batcher.plan(members)
                    carry.extend(leftover)
                    pack_t1 = time.perf_counter()
                    self.stats.pack_ms.observe((pack_t1 - t0) * 1e3)
                except Exception as e:  # packing failure: fail the group
                    self._fail(members, e, "failed")
                    continue
                try:
                    self._dispatch(plan, model_id=mid,
                                   pack_interval=(t0, pack_t1))
                except Exception as e:  # model failure: fail ONLY the
                    # dispatched batch's unfulfilled requests and keep
                    # serving — carry was never in this batch and gets
                    # its try next iteration (one poison batch must not
                    # take the engine or innocent leftovers down)
                    self._fail([r for r, _ in plan.entries
                                if not r.future.done()], e, "failed")

    def _fail(self, requests, exc, counter):
        for r in requests:
            self.stats.bump(counter)
            self.tenants.observe_event(
                r.tenant, r.tenant_class,
                r.model_id or self._models.default_id(), counter)
            r.span.end(error=repr(exc))
            r.future.set_exception(exc)

    def _queue_span(self, req):
        """Synthesized queue-wait child span (submit → drain)."""
        if req.t_drain is not None and req.span.span_id is not None:
            _spans.record_span("serving/queue", req.trace_id,
                               parent_id=req.span.span_id,
                               mono_start=req.t_submit,
                               mono_end=req.t_drain,
                               attrs={"engine": self.engine_id})

    def _bump_cc(self, result):
        with self._shapes_lock:
            self._cc_counts[result] += 1
        self._compile_cache[result].inc()

    def _compile_forward(self, plan, fn=None):
        """First-visit forward: open the compile window (watchdog
        grace) and classify the outcome against the jax cache events
        — a disk-served compile (persistent_hit: trace + cache fetch)
        vs a fresh backend build (miss). The event tally is process-
        global, so a CONCURRENT compile on another engine can only
        downgrade a true persistent_hit to miss (its miss events leak
        into this window), never invent one — the warm-restart signal
        stays conservative. Returns (seq, result, t0, t1)."""
        cc_before = compile_cache.events_snapshot()
        self._compiling_since = time.monotonic()
        t0 = time.perf_counter()
        try:
            seq = self._forward(plan, fn)
        finally:
            # refresh the heartbeat IN the same step that closes the
            # window: a probe (or the router's wedge check) must never
            # see the compile flag already cleared while the beat is
            # still as old as the whole compile
            self._beat = time.monotonic()
            self._compiling_since = None
        t1 = time.perf_counter()
        result = compile_cache.classify(
            cc_before, compile_cache.events_snapshot())
        self._bump_cc(result)
        return seq, result, t0, t1

    def _dispatch(self, plan, model_id=None, pack_interval=None):
        mid, fn = self._models.resolve(model_id)
        shape = (mid, plan.rows, plan.row_len)
        with self._shapes_lock:
            hit = shape in self._seen_shapes
        if hit:
            self._bump_cc("memory_hit")
            t0 = time.perf_counter()
            seq = self._forward(plan, fn)
            t1 = time.perf_counter()
            dt_ms = (t1 - t0) * 1e3
            self.stats.compute_ms.observe(dt_ms)
        else:
            _events.emit("compile_begin", engine_id=self.engine_id,
                         model=mid, rows=plan.rows,
                         row_len=plan.row_len)
            seq, result, t0, t1 = self._compile_forward(plan, fn)
            dt_ms = (t1 - t0) * 1e3
            # first visit pays trace+compile; report it as compile
            # latency, not as a (wildly misleading) compute sample
            with self._shapes_lock:
                self._seen_shapes.add(shape)
            self.stats.bump("compiles")
            self.stats.compile_ms.observe(dt_ms)
            _events.emit("compile_end", engine_id=self.engine_id,
                         model=mid, rows=plan.rows,
                         row_len=plan.row_len,
                         result=result, ms=round(dt_ms, 3))
        dt_s = t1 - t0
        self.costs.observe_batch(plan.row_len, dt_s, len(plan.entries),
                                 plan.valid_tokens, compiled=not hit)
        self.stats.observe_batch(plan.rows, plan.row_len,
                                 plan.valid_tokens, len(plan.entries),
                                 plan.row_len)
        # one line per batch (not per request): every served request's
        # trace id is findable in the event log without per-request spam
        _events.emit("batch_dispatch", engine_id=self.engine_id,
                     model=mid, rows=plan.rows,
                     row_len=plan.row_len, requests=len(plan.entries),
                     valid_tokens=plan.valid_tokens, ms=round(dt_ms, 3),
                     trace_ids=[r.trace_id for r, _ in plan.entries])
        self._last_dispatch = time.monotonic()
        now = time.monotonic()
        # per-request span trees: batch stages (pack, compile/forward)
        # time ONCE, but every member request's tree shows them — the
        # acceptance shape submit → queue → pack → compile/forward →
        # complete under one trace id
        fwd_name = "serving/forward" if hit else "serving/compile"
        fwd_attrs = {"rows": plan.rows, "row_len": plan.row_len,
                     "requests": len(plan.entries), "compiled": not hit,
                     "engine": self.engine_id}
        for req, pl in plan.entries:
            # amortized cost attribution: the batch's forward wall,
            # split by token share, rides the future so callers (and
            # the router/loadgen cross-checks) see what THIS request
            # cost the device. Shares sum to the batch time exactly —
            # the ledger-exactness contract. Written before pool/
            # result so even a failing postprocess keeps its bill.
            share = (pl.length / plan.valid_tokens
                     if plan.valid_tokens else 0.0)
            req.future.cost = {"engine_id": self.engine_id,
                               "bucket": plan.row_len,
                               "model": mid,
                               "tenant": req.tenant,
                               "tenant_class": req.tenant_class,
                               "device_s": dt_s * share,
                               "compiled": not hit,
                               "tokens": pl.length,
                               "batch_requests": len(plan.entries)}
            self.tenants.observe_cost(req.tenant, req.tenant_class,
                                      mid, dt_s * share, pl.length)
            record_spans = req.span.span_id is not None
            if record_spans:
                self._queue_span(req)
                if pack_interval is not None:
                    _spans.record_span(
                        "serving/pack", req.trace_id,
                        parent_id=req.span.span_id,
                        start_us=int(pack_interval[0] * 1e6),
                        end_us=int(pack_interval[1] * 1e6),
                        attrs={"engine": self.engine_id})
                _spans.record_span(fwd_name, req.trace_id,
                                   parent_id=req.span.span_id,
                                   start_us=int(t0 * 1e6),
                                   end_us=int(t1 * 1e6),
                                   attrs=fwd_attrs)
            # stage stamps for the critical-path breakdown (wfq_wait
            # was stamped at drain). pack/t0/t1 were timed with
            # perf_counter for the span axis; the breakdown's wall
            # endpoints are time.monotonic(), so map them across —
            # the clocks share CLOCK_MONOTONIC on Linux but not
            # everywhere, and a mismatched epoch clips every interval
            # outside the wall (100% unattributed, silently).
            # The stage spans themselves are skipped — the legacy
            # serving/pack + serving/forward children already carry
            # the same intervals in the tree.
            if req.stages is not None:
                if pack_interval is not None:
                    _attribution.stamp(
                        req, "pack",
                        _spans.perf_to_mono(pack_interval[0]),
                        _spans.perf_to_mono(pack_interval[1]),
                        span=False)
                _attribution.stamp(
                    req, "compute" if hit else "compile",
                    _spans.perf_to_mono(t0), _spans.perf_to_mono(t1),
                    span=False)
            try:
                out = self._pool(
                    seq[pl.row, pl.offset:pl.offset + pl.length], req)
            except Exception as e:  # a bad pool callable fails ITS
                # request, not the rest of the batch
                self.stats.bump("failed")
                req.span.end(error=repr(e))
                if self._capture is not None:
                    self._capture.record_request(
                        req, None, "failed",
                        (now - req.t_submit) * 1e3, model=mid,
                        version=self._models.versions().get(mid),
                        engine_id=self.engine_id)
                req.future.set_exception(e)
                continue
            req.t_done = now
            self.stats.queue_ms.observe((req.t_drain - req.t_submit) * 1e3)
            total_ms = (now - req.t_submit) * 1e3
            # OpenMetrics exemplar: links a firing latency alert
            # straight to a RETRIEVABLE trace at /traces/<id>
            self.stats.total_ms.observe(
                total_ms, exemplar=slow_exemplar(
                    req.trace_id, total_ms, self._exemplars))
            self.stats.bump("completed")
            self.tenants.observe_event(req.tenant, req.tenant_class,
                                       mid, "completed")
            self.tenants.observe_latency(req.tenant, req.tenant_class,
                                         mid, total_ms)
            if record_spans:
                _spans.record_span("serving/complete", req.trace_id,
                                   parent_id=req.span.span_id,
                                   start_us=int(t1 * 1e6),
                                   attrs={"engine": self.engine_id})
            if req.stages is not None:
                breakdown = _attribution.breakdown_from_stamps(
                    req.stages, req.t_submit, now,
                    trace_id=req.trace_id)
                req.future.breakdown = breakdown
                _attribution.aggregator(self.engine_id).observe(
                    breakdown, tenant_class=req.tenant_class,
                    model=mid, trace_id=req.trace_id)
            req.span.end()
            # capture AFTER breakdown/cost landed on the future (the
            # record carries both) and BEFORE the result fires, so a
            # caller observing completion finds its record durable
            if self._capture is not None:
                self._capture.record_request(
                    req, out, "completed", total_ms, model=mid,
                    version=self._models.versions().get(mid),
                    engine_id=self.engine_id)
            req.future.set_result(out)

    def _forward(self, plan, fn=None):
        ids = nd.array(plan.data, dtype="int32", ctx=self._ctx)
        tt = nd.array(plan.token_types, dtype="int32", ctx=self._ctx)
        vl = nd.array(plan.valid_length, dtype="int32", ctx=self._ctx)
        seg = nd.array(plan.segment_ids, dtype="int32", ctx=self._ctx)
        pos = nd.array(plan.positions, dtype="int32", ctx=self._ctx)
        model = fn if fn is not None else self._model
        # the batch adopts its requests' trace ids so the forward span
        # in the Chrome trace / xprof names every request it served
        with self._forward_lock:
            with _trace_context(
                    _join_trace_ids(r for r, _ in plan.entries)):
                with autograd.predict_mode():
                    with profiler.Scope("serving/forward"):
                        out = model(ids, tt, vl, seg, pos)
        if isinstance(out, (list, tuple)):
            out = out[0]
        return out.asnumpy()   # host sync: per-request slicing follows

    def _forward_shape(self, rows, row_len, model_id=None, fn=None):
        """One dummy forward at (rows, row_len) — warmup helper.
        Counts in the compile-cache split like a live dispatch (a
        manifest replay against a primed persistent cache records
        ``persistent_hit``s — the warm-restart acceptance signal).

        With an explicit ``fn`` (the hot-swap warm-replay: a NEW model
        version not yet in the registry) the forward always takes the
        compile path and the shape is NOT marked seen — it already is
        under its model id, and the incoming version must not poison
        the seen-set if its replay fails mid-swap."""
        from .batcher import PackedPlan

        data = np.zeros((rows, row_len), np.int32)
        seg = np.zeros((rows, row_len), np.int32)
        seg[:, 0] = 1
        plan = PackedPlan(data, np.zeros_like(data), seg,
                          np.zeros_like(data), np.ones(rows, np.int32),
                          entries=[], pad_rows=rows)
        if fn is not None:
            _seq, _result, t0, t1 = self._compile_forward(plan, fn)
            self.costs.observe_warmup(row_len, t1 - t0, compiled=True)
            return
        mid, _fn = self._models.resolve(model_id)
        shape = (mid, rows, row_len)
        with self._shapes_lock:
            seen = shape in self._seen_shapes
        if seen:
            t0 = time.perf_counter()
            self._forward(plan, _fn)
            self.costs.observe_warmup(row_len, time.perf_counter() - t0,
                                      compiled=False)
            self._bump_cc("memory_hit")
        else:
            _seq, _result, t0, t1 = self._compile_forward(plan, _fn)
            self.costs.observe_warmup(row_len, t1 - t0, compiled=True)
            # mark seen only AFTER the forward succeeded: a failed
            # warmup replay must leave the shape cold so the first
            # live dispatch still gets the compile path (grace window
            # + compile_ms accounting), not a phantom memory_hit
            with self._shapes_lock:
                self._seen_shapes.add(shape)
