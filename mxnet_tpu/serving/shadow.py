"""Shadow-diff validation: mirror live traffic at a candidate, gate
the hot-swap on the verdict.

``ShadowMirror`` is the router's pre-swap evidence engine. While a
candidate model version warms on a *shadow seat* (a local engine
handle or a wire ``host:port`` peer that is NOT in the live rotation),
the router mirrors a configured fraction of real traffic at it —
strictly fire-and-forget off the hot path:

- the mirror decision + dispatch happen AFTER the live request's
  future has resolved; the live caller never waits on the shadow;
- wire mirroring rides :class:`~.wire.WireClient` (``dispatch`` is
  queue-a-frame, no blocking I/O; the blocking ``ensure()`` handshake
  runs on the router's poll thread via :meth:`maintain`);
- shadow failures are counted, never raised — a dead candidate makes
  the verdict inconclusive, not the router unhealthy.

Each mirrored completion is diffed against its primary: output byte
digests (the :func:`~.capture.output_digest` contract shared with the
capture/replay oracle — seeded decodes make a faithful candidate
byte-identical; float outputs fall back to the same ~1e-5 tolerance
replay uses, because the shadow seat's different packing moves fp
results by ~1 ulp) and latency. The running verdict is exposed as
``mxnet_tpu_shadow_*`` families + the ``/shadow`` body, and
:meth:`gate` is the callable ``swap_model(..., gate=...)`` consults:
the flip is REFUSED (:class:`SwapGateError`) while the divergence rate
exceeds ``MXNET_TPU_SHADOW_THRESHOLD`` or fewer than
``MXNET_TPU_SHADOW_MIN_REQUESTS`` comparisons have landed.

``MXNET_TPU_SHADOW=0`` (default) builds nothing: no thread, no metric
families, no mirror branch in the router's completion path.
"""
from __future__ import annotations

import collections
import threading
import time

import numpy as np

from .. import envvars
from ..telemetry import events as _events
from ..telemetry.registry import REGISTRY as _REGISTRY
from .capture import is_synthetic, output_digest
from .metrics import LatencySummary

__all__ = ["ShadowMirror", "SwapGateError"]


class SwapGateError(RuntimeError):
    """``swap_model`` refused: the shadow-diff gate is not passing.
    The live model keeps serving; the candidate stays shadowed."""


class ShadowMirror:
    """Mirrors a sampled fraction of completed live requests at a
    candidate seat and keeps the divergence verdict.

    Built by the router's ``start()`` only when ``MXNET_TPU_SHADOW``
    is on; armed/disarmed at runtime with :meth:`set_target` /
    :meth:`clear_target` (arming resets the verdict — each candidate
    earns its own evidence)."""

    def __init__(self, owner_id):
        self.owner_id = str(owner_id)
        self.fraction = min(1.0, max(
            0.0, envvars.get("MXNET_TPU_SHADOW_FRACTION")))
        self.threshold = max(
            0.0, envvars.get("MXNET_TPU_SHADOW_THRESHOLD"))
        self.min_requests = max(
            1, envvars.get("MXNET_TPU_SHADOW_MIN_REQUESTS"))
        self.timeout_s = max(
            0.1, envvars.get("MXNET_TPU_SHADOW_TIMEOUT_S"))
        self._lock = threading.Lock()
        self._accum = 0.0           # deterministic mirror-fraction credit
        self._target = None         # engine handle (duck-typed submit)
        self._client = None         # or a WireClient to a remote seat
        self.model_id = None
        self.version = None
        self._armed_at = None
        self._reset_counts_locked()
        c = _REGISTRY.counter(
            "mxnet_tpu_shadow_requests_total",
            "shadow-mirror outcomes per completed live request: "
            "mirrored (dispatched to the candidate), match, divergence "
            "(digest mismatch), error (candidate failed), skipped "
            "(fraction-sampled out), synthetic (canary, excluded), "
            "unavailable (no live shadow connection)",
            ("owner", "result"))
        self._c = {r: c.labels(owner=self.owner_id, result=r)
                   for r in ("mirrored", "match", "divergence", "error",
                             "skipped", "synthetic", "unavailable")}
        self._c_div = _REGISTRY.counter(
            "mxnet_tpu_shadow_divergences_total",
            "mirrored requests whose candidate output digest differed "
            "from the primary's", ("owner",)).labels(owner=self.owner_id)
        hist = _REGISTRY.histogram(
            "mxnet_tpu_shadow_latency_ms",
            "end-to-end latency of compared request pairs, primary vs "
            "shadow leg", ("owner", "which"))
        self._lat = {
            "primary": LatencySummary(
                hist=hist.labels(owner=self.owner_id, which="primary")),
            "shadow": LatencySummary(
                hist=hist.labels(owner=self.owner_id, which="shadow"))}
        _events.emit("shadow_start", owner=self.owner_id,
                     fraction=self.fraction, threshold=self.threshold,
                     min_requests=self.min_requests)

    def _reset_counts_locked(self):
        self.mirrored = 0
        self.compared = 0
        self.matched = 0
        self.divergences = 0
        self.errors = 0
        self._recent = collections.deque(maxlen=8)

    # -- arming ------------------------------------------------------------
    def set_target(self, target, model_id=None, version=None,
                   fraction=None):
        """Arm the mirror at a candidate seat. ``target`` is either an
        in-process engine handle (anything with ``submit`` /
        ``submit_payload``) or a ``"host:port"`` wire address of a
        remote engine's dispatch listener. Resets the verdict."""
        client = None
        if isinstance(target, str):
            from .wire import WireClient
            host, _, port = target.rpartition(":")
            client = WireClient(host or "127.0.0.1", int(port),
                                client_id=f"shadow:{self.owner_id}",
                                timeout_s=self.timeout_s)
            target = None
        old = None
        with self._lock:
            old = self._client
            self._target = target
            self._client = client
            self.model_id = str(model_id) if model_id else None
            self.version = str(version) if version is not None else None
            if fraction is not None:
                self.fraction = min(1.0, max(0.0, float(fraction)))
            self._armed_at = time.monotonic()
            self._reset_counts_locked()
            self._lat["primary"] = LatencySummary(
                hist=self._lat["primary"]._hist)
            self._lat["shadow"] = LatencySummary(
                hist=self._lat["shadow"]._hist)
        if old is not None:
            old.close()
        _events.emit("shadow_arm", owner=self.owner_id,
                     model=self.model_id, version=self.version,
                     remote=client is not None)

    def clear_target(self):
        """Disarm (candidate withdrawn or promoted). The verdict stays
        readable until the next :meth:`set_target`."""
        with self._lock:
            old, self._client = self._client, None
            self._target = None
            self._armed_at = None
        if old is not None:
            old.close()
        _events.emit("shadow_disarm", owner=self.owner_id)

    @property
    def active(self):
        return self._target is not None or self._client is not None

    def maintain(self):
        """Blocking connection upkeep for a wire target — the router
        calls this from its health-poll thread (never the dispatcher),
        mirroring the seat clients' own ``ensure()`` discipline."""
        client = self._client
        if client is not None:
            client.ensure()

    # -- the mirror point (router completion path) -------------------------
    def mirror(self, req, value, primary_ms):
        """Fire-and-forget mirror of one COMPLETED live request.
        Called after the live future has resolved; everything past
        this line is invisible to the live caller. Synthetic canary
        probes never mirror; real traffic is fraction-sampled by the
        same deterministic credit accumulator capture uses."""
        if not self.active:
            return False
        if is_synthetic(req.trace_id):
            self._c["synthetic"].inc()
            return False
        with self._lock:
            self._accum += self.fraction
            if self._accum < 1.0:
                sampled = False
            else:
                self._accum -= 1.0
                sampled = True
        if not sampled:
            self._c["skipped"].inc()
            return False
        payload = dict(req.decode or {},
                       tokens=np.asarray(req.tokens, np.int32),
                       stream=False,
                       trace_id=f"shadow-{req.trace_id}",
                       model_id=self.model_id or req.model_id,
                       tenant=req.tenant, tenant_class=req.tenant_class)
        expected = output_digest(value)
        # float primaries keep their VALUES for the comparison: the
        # shadow seat packs the mirrored request differently, which
        # moves fp outputs by ~1 ulp (capture.py module docstring) —
        # digest equality stays the int/token contract
        ref = None
        if value is not None:
            arr = np.asarray(value)
            if arr.dtype.kind == "f":
                ref = np.ascontiguousarray(arr)
        t0 = time.monotonic()

        def _done(exc, out):
            self._observe(req.trace_id, expected, ref, exc, out,
                          primary_ms, (time.monotonic() - t0) * 1e3)

        client = self._client
        if client is not None:
            if not client.has_live():
                self._c["unavailable"].inc()
                return False
            try:
                client.dispatch(payload, on_done=lambda exc, body:
                                _done(exc, (body or {}).get("result")
                                      if exc is None else None),
                                timeout_s=self.timeout_s)
            except Exception as e:
                self._c["error"].inc()
                _events.emit("shadow_dispatch_error",
                             owner=self.owner_id, error=repr(e))
                return False
        else:
            target = self._target
            try:
                sp = getattr(target, "submit_payload", None)
                if sp is not None and req.decode:
                    fut, _streamed = sp(payload)
                else:
                    fut = target.submit(
                        payload["tokens"], trace_id=payload["trace_id"],
                        model_id=payload["model_id"], tenant=req.tenant,
                        tenant_class=req.tenant_class)
                # runs on the shadow engine's worker at completion —
                # still nowhere near the live caller
                def _cb(f):
                    exc = f.exception(timeout=0)
                    _done(exc, f.result(timeout=0) if exc is None
                          else None)

                fut.add_done_callback(_cb)
            except Exception as e:
                self._c["error"].inc()
                _events.emit("shadow_submit_error",
                             owner=self.owner_id, error=repr(e))
                return False
        with self._lock:
            self.mirrored += 1
        self._c["mirrored"].inc()
        return True

    def _observe(self, trace_id, expected, ref, exc, out, primary_ms,
                 shadow_ms):
        if exc is not None:
            with self._lock:
                self.errors += 1
            self._c["error"].inc()
            _events.emit("shadow_error", owner=self.owner_id,
                         trace_id=trace_id, error=repr(exc))
            return
        got = output_digest(out)
        self._lat["primary"].observe(primary_ms, exemplar=trace_id)
        self._lat["shadow"].observe(shadow_ms, exemplar=trace_id)
        diverged = got != expected
        max_diff = None
        if diverged and ref is not None and out is not None:
            got_arr = np.asarray(out)
            if got_arr.shape == ref.shape and got_arr.dtype.kind == "f":
                max_diff = float(np.max(np.abs(
                    got_arr.astype(np.float64)
                    - ref.astype(np.float64)))) if ref.size else 0.0
                diverged = not np.allclose(got_arr, ref,
                                           rtol=1e-5, atol=1e-5)
        with self._lock:
            self.compared += 1
            if diverged:
                self.divergences += 1
                self._recent.append(
                    {"trace_id": trace_id, "expected": expected,
                     "got": got, "max_abs_diff": max_diff,
                     "primary_ms": round(primary_ms, 3),
                     "shadow_ms": round(shadow_ms, 3)})
            else:
                self.matched += 1
        if diverged:
            self._c["divergence"].inc()
            self._c_div.inc()
            _events.emit("shadow_divergence", owner=self.owner_id,
                         trace_id=trace_id, expected=expected, got=got)
        else:
            self._c["match"].inc()

    # -- the verdict -------------------------------------------------------
    def divergence_rate(self):
        with self._lock:
            return (self.divergences / self.compared
                    if self.compared else None)

    def verdict(self):
        """The ``/shadow`` body: configuration, evidence so far, the
        pass/fail call (None until ``min_requests`` comparisons have
        landed), and the recent divergences for triage."""
        with self._lock:
            compared = self.compared
            rate = (self.divergences / compared) if compared else None
            body = {"owner": self.owner_id, "enabled": True,
                    "active": self.active,
                    "model": self.model_id, "version": self.version,
                    "fraction": self.fraction,
                    "threshold": self.threshold,
                    "min_requests": self.min_requests,
                    "mirrored": self.mirrored, "compared": compared,
                    "matched": self.matched,
                    "divergences": self.divergences,
                    "errors": self.errors,
                    "divergence_rate": (round(rate, 6)
                                        if rate is not None else None),
                    "armed_s": (round(time.monotonic()
                                      - self._armed_at, 3)
                                if self._armed_at else None),
                    "recent_divergences": list(self._recent)}
        body["passing"] = (None if compared < self.min_requests
                           else rate <= self.threshold)
        body["latency"] = {k: v.snapshot()
                           for k, v in self._lat.items()}
        return body

    def gate(self):
        """The ``swap_model`` gate contract: ``(ok, reason)``. Refuses
        while evidence is insufficient or the divergence rate is over
        threshold — a candidate must EARN the flip."""
        with self._lock:
            compared, divergences = self.compared, self.divergences
        if not self.active and compared == 0:
            return False, "shadow mirror not armed (no evidence)"
        if compared < self.min_requests:
            return False, (f"insufficient shadow sample: {compared}/"
                           f"{self.min_requests} comparisons")
        rate = divergences / compared
        if rate > self.threshold:
            return False, (f"shadow divergence rate {rate:.4f} exceeds "
                           f"threshold {self.threshold:.4f} "
                           f"({divergences}/{compared} diverged)")
        return True, (f"shadow verdict passing: {divergences}/"
                      f"{compared} diverged (rate {rate:.4f} <= "
                      f"{self.threshold:.4f})")

    def close(self):
        self.clear_target()
        _events.emit("shadow_stop", owner=self.owner_id)
