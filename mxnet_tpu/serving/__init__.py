"""Packed continuous-batching inference serving (`mxnet_tpu.serving`).

An in-process model server for encoder-style models: a bounded
request queue with admission control, a continuous batcher that
first-fit-packs variable-length requests into a small closed set of
fixed packed-row shapes (io/packing.py + the flash kernel's
``segment_ids`` path — no request pays padding it didn't bring), one
worker thread running the hybridized forward, and an observability
surface (latency percentiles, queue depth, packing efficiency).

Reference lineage: MXNet Model Server's queue → batcher → backend
worker, rebuilt around iteration-level (Orca-style) scheduling and
shape-bucketed compiled executors (the BucketingModule heritage).

Scale-out: :class:`~.router.ServingRouter` fronts N engines
(in-process handles or remote ``expose()`` endpoints) with
least-outstanding routing, failover requeue, engine-labeled metric
aggregation, cross-engine trace merging, and a per-engine health
scoreboard — see ``router.py``.

Multi-tenancy: a :class:`~.tenancy.ModelRegistry` lets one engine
host several named models (hot-swappable via ``swap_model``), the
queue runs weighted-fair admission over tenant classes
(priority/standard/best-effort), and every request carries
``model_id``/``tenant``/``tenant_class`` through the router, wire
protocol and HA journal — see ``tenancy.py``.

Quickstart::

    from mxnet_tpu.gluon.model_zoo import bert_base
    from mxnet_tpu.gluon.model_zoo.bert import bert_serving_entry
    from mxnet_tpu.serving import ServingEngine

    net = bert_base()
    net.initialize(...)
    engine = ServingEngine(bert_serving_entry(net), pool="mean",
                           bucket_lens=(64, 256, 512), max_rows=8)
    with engine:                       # start; stop(drain=True) on exit
        fut = engine.submit(token_ids, deadline_ms=200)
        embedding = fut.result()
        print(engine.snapshot()["latency"]["total"])
"""
from .queue import (ServingError, QueueFullError, DeadlineExceededError,
                    RequestTooLongError, EngineStoppedError,
                    InvalidSamplingError, InferenceFuture, Request,
                    RequestQueue, validate_sampling)
from .tenancy import (TENANT_CLASSES, ModelRegistry, TenantStats,
                      UnknownModelError, class_weights,
                      normalize_class)
from .batcher import ContinuousBatcher, DecodeSlots, PackedPlan
from .metrics import DecodeStats, LatencySummary, ServingStats
from .engine import ServingEngine
from .kvcache import KVPagesExhaustedError, PagedKVPool
from .decode import DecodeEngine, DecodeRequest
from .decode_model import PagedCausalLM
from .router import (ServingRouter, NoEngineAvailableError,
                     RemoteEngineError)
from .autoscaler import FleetAutoscaler
from .chaos import ChaosController
from .capture import CaptureStore, load_corpus, output_digest, replay
from .shadow import ShadowMirror, SwapGateError

__all__ = ["ServingEngine", "DecodeEngine", "ServingRouter",
           "CaptureStore", "ShadowMirror", "SwapGateError",
           "load_corpus", "output_digest", "replay",
           "FleetAutoscaler", "ChaosController", "ContinuousBatcher",
           "DecodeSlots", "PackedPlan", "PagedKVPool", "PagedCausalLM",
           "DecodeRequest", "KVPagesExhaustedError",
           "RequestQueue", "Request", "InferenceFuture",
           "LatencySummary", "ServingStats", "DecodeStats",
           "ServingError", "QueueFullError", "DeadlineExceededError",
           "RequestTooLongError", "EngineStoppedError",
           "InvalidSamplingError", "validate_sampling",
           "NoEngineAvailableError", "RemoteEngineError",
           "TENANT_CLASSES", "ModelRegistry", "TenantStats",
           "UnknownModelError", "class_weights", "normalize_class"]
