"""Deterministic fault injection: prove the fleet heals, on a script.

Every self-healing mechanism the serving stack now has — failover
requeue, SLO-aware routing weights, the autoscaler, router
active/active adoption — is only trustworthy if it is EXERCISED, and
production exercises it at the worst time. This module injects the
faults on purpose, deterministically:

- a :class:`ChaosController` owns a scripted **schedule** (a sorted
  list of ``{at, fault, target, ...}`` entries), an injectable
  **clock** and a seeded **rng** — the same
  ``MXNET_TPU_CHAOS_SEED`` + schedule replays an IDENTICAL fault
  sequence (the event-log golden in ``tests/test_chaos.py`` pins
  this). Each applied fault gets its OWN rng stream derived from
  ``(seed, fault sequence number)``, so a probabilistic fault's draw
  pattern is deterministic per fault even when overlapping faults
  draw concurrently from different threads;
- faults act on live registered targets (engines/routers register at
  ``start()`` when ``MXNET_TPU_CHAOS=1``), emitting ``chaos_*`` run
  events so incidents and flight bundles can attribute an induced
  fault as induced.

Fault vocabulary (``fault`` key of a schedule entry):

==============  ============================================================
``hotspot``     slow ``target`` engine's forwards by ``ms`` for
                ``duration_s`` (wraps the model callable; restores after)
``wedge``       block ``target`` engine's forwards entirely for
                ``duration_s`` (the worker thread stays alive — the
                lying-healthz shape)
``kill_wire``   abruptly close the target engine's accepted wire
                connections (router side reconnects; in-flight work
                fails over)
``drop_frames`` drop inbound dispatch frames on the target engine's
                wire listener with probability ``p`` for ``duration_s``
``delay_frames`` delay inbound dispatch frames by ``ms`` for
                ``duration_s``
``kill_engine`` stop the target engine abruptly (``stop(drain=False)``)
                — or ``SIGKILL`` when ``target`` is a pid
``kill_router`` abrupt router death (``ServingRouter.die()``: nothing
                drained, nothing resolved — the HA drill's trigger)
==============  ============================================================

Off is FREE: with ``MXNET_TPU_CHAOS=0`` (the default) nothing
registers, no thread spawns, no metric family exists, and no model
callable or wire path is wrapped — the disabled-path test asserts the
identities, matching the mxsan pattern.
"""
from __future__ import annotations

import json
import os
import random
import signal
import threading
import time

from .. import envvars
from ..telemetry import events as _events
from ..telemetry.registry import REGISTRY as _REGISTRY

__all__ = ["ChaosController", "chaos_enabled", "controller",
           "register_engine", "register_router", "reset",
           "load_schedule", "FAULTS"]

FAULTS = ("hotspot", "wedge", "kill_wire", "drop_frames",
          "delay_frames", "kill_engine", "kill_router")


def chaos_enabled():
    return bool(envvars.get("MXNET_TPU_CHAOS"))


def load_schedule(spec):
    """Parse a schedule spec: a list (already parsed), inline JSON, or
    a path to a JSON file. Returns a list of entry dicts."""
    if spec is None:
        return []
    if isinstance(spec, (list, tuple)):
        entries = list(spec)
    else:
        text = str(spec).strip()
        if not text:
            return []
        if not text.startswith("["):
            with open(text) as f:
                text = f.read()
        entries = json.loads(text)
    out = []
    for e in entries:
        if not isinstance(e, dict) or "fault" not in e:
            raise ValueError(f"bad chaos schedule entry: {e!r}")
        if e["fault"] not in FAULTS:
            raise ValueError(f"unknown chaos fault {e['fault']!r} "
                             f"(have {FAULTS})")
        out.append(dict(e))
    out.sort(key=lambda e: float(e.get("at", 0.0)))
    return out


class _SlowModel:
    """Hot-spot wrapper around an engine's model callable: every
    forward pays an extra ``delay_s`` (rng-jittered ±20% so repeated
    forwards don't phase-lock, drawn from the CONTROLLER's seeded rng
    — deterministic under a pinned seed)."""

    def __init__(self, fn, delay_s, rng, sleep):
        self.fn = fn
        self.delay_s = float(delay_s)
        self._rng = rng
        self._sleep = sleep

    def __call__(self, *args):
        self._sleep(self.delay_s * (0.8 + 0.4 * self._rng.random()))
        return self.fn(*args)


class _WedgedModel:
    """Wedge wrapper: forwards spin while ``gate`` is set — the worker
    THREAD stays alive (self-reported health stays green), nothing
    completes. Exactly the lying-healthz shape the canary pages on."""

    def __init__(self, fn, sleep):
        self.fn = fn
        self.gate = threading.Event()
        self.gate.set()
        self._sleep = sleep

    def __call__(self, *args):
        while self.gate.is_set():
            self._sleep(0.01)
        return self.fn(*args)


class ChaosController:
    """One scripted fault campaign over a set of registered targets.

    Parameters
    ----------
    schedule : schedule spec (see :func:`load_schedule`); entries fire
        at ``at`` seconds after :meth:`start` (or are driven manually
        via :meth:`apply` — the scripted-clock test path).
    seed : rng seed (default ``MXNET_TPU_CHAOS_SEED``) — the ONLY
        randomness source for probabilistic faults.
    clock / sleep : injectable monotonic clock and sleep so the
        determinism golden runs without real time passing.
    """

    def __init__(self, schedule=None, seed=None, clock=None,
                 sleep=None, registry=None):
        reg = registry if registry is not None else _REGISTRY
        if schedule is None:
            schedule = envvars.get("MXNET_TPU_CHAOS_SCHEDULE")
        self.schedule = load_schedule(schedule)
        self.seed = (int(seed) if seed is not None
                     else envvars.get("MXNET_TPU_CHAOS_SEED"))
        self._rng = random.Random(self.seed)
        self._fault_rng = self._rng     # re-derived per applied fault
        self._clock = clock if clock is not None else time.monotonic
        self._sleep = sleep if sleep is not None else time.sleep
        self._engines = {}          # engine_id -> ServingEngine
        self._routers = {}          # router_id -> ServingRouter
        # engine_id -> STACK of (kind, wrapper, orig): overlapping
        # faults on one engine nest, and each clear unlinks ITS
        # wrapper (top via eng._model, inner via the outer's .fn)
        self._wrapped = {}
        # engine_id -> (fault_kind, hook): ONE frame fault at a time
        # per engine (a newer one replaces the older; the older's
        # scheduled clear then becomes a no-op instead of cancelling
        # the newer fault)
        self._frame_hooks = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._t0 = None
        self._seq = 0
        self._c_faults = reg.counter(
            "mxnet_tpu_chaos_faults_total",
            "chaos faults injected, by fault kind", ("fault",))
        _events.emit("chaos_armed", seed=self.seed,
                     schedule=len(self.schedule))

    # -- target registry ----------------------------------------------------
    def register_engine(self, engine):
        with self._lock:
            self._engines[str(engine.engine_id)] = engine
        return self

    def register_router(self, router):
        with self._lock:
            self._routers[str(router.router_id)] = router
        return self

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        """Walk the schedule on a daemon thread against the (possibly
        injected) clock. Idempotent."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._t0 = self._clock()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="mxnet_tpu_chaos")
            self._thread.start()
        _events.emit("chaos_start", seed=self.seed,
                     schedule=len(self.schedule))
        return self

    def stop(self, clear=True):
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)
        if clear:
            self.clear_all()
        _events.emit("chaos_stop", injected=self._seq)

    def _run(self):
        # the timeline holds injections AND their scheduled clears so
        # both replay in one deterministic order
        timeline = []
        for i, e in enumerate(self.schedule):
            at = float(e.get("at", 0.0))
            timeline.append((at, 0, i, "apply", e))
            dur = e.get("duration_s")
            if dur is not None:
                timeline.append((at + float(dur), 1, i, "clear", e))
        timeline.sort(key=lambda x: (x[0], x[1], x[2]))
        for at, _phase, _i, action, entry in timeline:
            while not self._stop.is_set():
                remaining = (self._t0 + at) - self._clock()
                if remaining <= 0:
                    break
                self._stop.wait(min(0.05, max(0.001, remaining)))
            if self._stop.is_set():
                return
            try:
                if action == "apply":
                    self.apply(entry)
                else:
                    self.clear(entry)
            except Exception as e:
                _events.emit("chaos_error", fault=entry.get("fault"),
                             target=entry.get("target"), error=repr(e))

    # -- fault application (also the scripted-clock test surface) -----------
    def apply(self, entry):
        """Inject one fault NOW (schedule thread, or a test driving a
        scripted campaign). Emits ``chaos_fault``."""
        fault = entry["fault"]
        target = entry.get("target")
        self._seq += 1
        _events.emit("chaos_fault", seq=self._seq, fault=fault,
                     target=target, at=entry.get("at"),
                     duration_s=entry.get("duration_s"),
                     ms=entry.get("ms"), p=entry.get("p"))
        self._c_faults.labels(fault=fault).inc()
        # per-fault rng stream: deterministic from (seed, seq) and
        # private to this fault — overlapping faults drawing from
        # different threads cannot perturb each other's sequences.
        # (int seed: tuple seeding is hash-based and gone in py3.11)
        self._fault_rng = random.Random(
            (self.seed << 32) ^ (self._seq & 0xffffffff))
        # the schedule walker clears with the SAME entry dict it
        # applied: the tag lets a clear unlink exactly ITS wrapper
        # even when two same-kind faults overlap on one engine
        entry["_chaos_tag"] = self._seq
        getattr(self, f"_apply_{fault}")(entry)

    def clear(self, entry):
        """Clear one duration fault (restore the wrapped/hooked
        path). Emits ``chaos_fault_cleared``."""
        fault = entry["fault"]
        target = str(entry.get("target"))
        if fault in ("hotspot", "wedge"):
            self._unwrap(target, kind=fault,
                         tag=entry.get("_chaos_tag"))
        elif fault in ("drop_frames", "delay_frames"):
            # identity-checked: only the fault whose hook is STILL
            # installed may null it — a drop fault's scheduled clear
            # must not cancel a delay fault armed after it
            with self._lock:
                rec = self._frame_hooks.get(target)
                owns = rec is not None and rec[0] == fault
                if owns:
                    self._frame_hooks.pop(target, None)
            if owns:
                eng = self._engine(target)
                if eng is not None and eng._wire is not None:
                    eng._wire.chaos_rx = None
        _events.emit("chaos_fault_cleared", fault=fault, target=target)

    def clear_all(self):
        with self._lock:
            wrapped = list(self._wrapped)
            engines = list(self._engines.values())
        for eid in wrapped:
            while True:
                with self._lock:
                    if not self._wrapped.get(eid):
                        break
                self._unwrap(eid)
        with self._lock:
            self._frame_hooks.clear()
        for eng in engines:
            if eng._wire is not None:
                eng._wire.chaos_rx = None

    # -- helpers ------------------------------------------------------------
    def _engine(self, target):
        with self._lock:
            eng = self._engines.get(str(target))
        if eng is None:
            _events.emit("chaos_error", fault="?", target=target,
                         error="no such registered engine")
        return eng

    def _wrap(self, eid, kind, wrapper, orig, tag=None):
        with self._lock:
            self._wrapped.setdefault(str(eid), []) \
                .append((kind, wrapper, orig, tag))

    def _unwrap(self, eid, kind=None, tag=None):
        """Remove the wrapper tagged ``tag`` (falling back to the
        newest of ``kind``, then the newest of any kind) from the
        engine's wrap stack: the top unlinks via ``eng._model``, an
        inner one by relinking the wrapper ABOVE it past it —
        overlapping faults (even same-kind) clear independently and
        ``clear_all`` always restores the original model."""
        eid = str(eid)
        eng = self._engine(eid)
        relink = None
        with self._lock:
            stack = self._wrapped.get(eid) or []
            idx = None
            if tag is not None:
                idx = next((i for i in range(len(stack) - 1, -1, -1)
                            if stack[i][3] == tag), None)
            if idx is None:
                idx = next((i for i in range(len(stack) - 1, -1, -1)
                            if kind is None or stack[i][0] == kind),
                           None)
            if idx is None:
                return
            k, wrapper, orig, _tag = stack.pop(idx)
            if idx < len(stack):
                # the wrapper above ours now wraps OUR orig — relink
                # it AND rewrite its record (its stored orig must stop
                # pointing at the wrapper we just removed)
                above_k, above_w, _, above_tag = stack[idx]
                stack[idx] = (above_k, above_w, orig, above_tag)
                relink = above_w
            if not stack:
                self._wrapped.pop(eid, None)
        if k == "wedge":
            wrapper.gate.clear()    # release spinning forwards first
        if eng is None:
            return
        if relink is not None:
            relink.fn = orig
        elif eng._model is wrapper:
            eng._model = orig

    # -- fault implementations ----------------------------------------------
    def _apply_hotspot(self, entry):
        eng = self._engine(entry.get("target"))
        if eng is None:
            return
        delay_s = float(entry.get("ms", 50.0)) / 1e3
        wrapper = _SlowModel(eng._model, delay_s, self._fault_rng,
                             self._sleep)
        self._wrap(eng.engine_id, "hotspot", wrapper, eng._model)
        eng._model = wrapper

    def _apply_wedge(self, entry):
        eng = self._engine(entry.get("target"))
        if eng is None:
            return
        wrapper = _WedgedModel(eng._model, self._sleep)
        self._wrap(eng.engine_id, "wedge", wrapper, eng._model)
        eng._model = wrapper

    def _apply_kill_wire(self, entry):
        target = str(entry.get("target"))
        eng = None
        with self._lock:
            eng = self._engines.get(target)
            routers = list(self._routers.values())
        killed = 0
        if eng is not None and eng._wire is not None:
            killed += eng._wire.kill_connections()
        else:
            # a router target: tear down its dispatch pools
            for r in routers:
                if r.router_id == target:
                    with r._lock:
                        seats = list(r._seats.values())
                    for seat in seats:
                        wire = getattr(seat, "_wire", None)
                        if wire is not None:
                            killed += wire.kill_connections()
        _events.emit("chaos_wire_killed", target=target,
                     connections=killed)

    def _frame_hook(self, mode, p, delay_s, rng=None):
        rng = rng if rng is not None else self._rng
        sleep = self._sleep

        def hook(tag):
            if tag != "SUBMIT":
                return True         # only dispatch frames are game
            if mode == "drop":
                if rng.random() < p:
                    _events.emit("chaos_frame_dropped", tag=tag)
                    return False
                return True
            sleep(delay_s)
            return True

        return hook

    def _arm_frame_fault(self, entry, kind, hook):
        eng = self._engine(entry.get("target"))
        if eng is None or eng._wire is None:
            return
        with self._lock:
            self._frame_hooks[str(entry.get("target"))] = (kind, hook)
        eng._wire.chaos_rx = hook

    def _apply_drop_frames(self, entry):
        self._arm_frame_fault(entry, "drop_frames", self._frame_hook(
            "drop", float(entry.get("p", 0.5)), 0.0,
            rng=self._fault_rng))

    def _apply_delay_frames(self, entry):
        self._arm_frame_fault(entry, "delay_frames", self._frame_hook(
            "delay", 1.0, float(entry.get("ms", 20.0)) / 1e3,
            rng=self._fault_rng))

    def _apply_kill_engine(self, entry):
        target = entry.get("target")
        eng = None
        with self._lock:
            eng = self._engines.get(str(target))
        if eng is None:
            # pid target: the cross-process kill (the only fault that
            # reaches outside this process)
            try:
                os.kill(int(target), signal.SIGKILL)
                _events.emit("chaos_process_killed", pid=int(target))
            except (ValueError, TypeError, OSError) as e:
                _events.emit("chaos_error", fault="kill_engine",
                             target=target, error=repr(e))
            return
        try:
            eng.stop(drain=False, timeout=10.0)
        except Exception as e:
            _events.emit("chaos_error", fault="kill_engine",
                         target=target, error=repr(e))

    def _apply_kill_router(self, entry):
        target = str(entry.get("target"))
        with self._lock:
            router = self._routers.get(target)
        if router is None:
            _events.emit("chaos_error", fault="kill_router",
                         target=target, error="no such router")
            return
        router.die()


# -- process singleton (env-gated) -------------------------------------------

_controller = None
_ctl_lock = threading.Lock()


def controller():
    """The process chaos controller — built from the environment on
    first use, None when ``MXNET_TPU_CHAOS=0`` (nothing is built,
    registered, patched or spawned)."""
    global _controller
    if not chaos_enabled():
        return None
    with _ctl_lock:
        if _controller is None:
            _controller = ChaosController()
            if _controller.schedule:
                _controller.start()
        return _controller


def register_engine(engine):
    """Engine start() hook: one env check when chaos is off."""
    ctl = controller()
    if ctl is not None:
        ctl.register_engine(engine)
    return ctl


def register_router(router):
    ctl = controller()
    if ctl is not None:
        ctl.register_router(router)
    return ctl


def reset():
    """Tests only: stop and forget the process controller."""
    global _controller
    with _ctl_lock:
        ctl, _controller = _controller, None
    if ctl is not None:
        ctl.stop()
