"""Reference decode model: a paged-KV causal LM the decode engine drives.

The encoder serving path adapts gluon blocks (``bert_serving_entry``);
autoregressive decode needs a model that THREADS THE KV CACHE through
every step, which the encoder CachedOp contract has no slot for. This
module provides the decode-side contract plus a self-contained
GPT-style reference implementation (:class:`PagedCausalLM`) the decode
engine, bench leg and tests drive:

- ``prefill(caches, ids, length, phys, off)`` — one padded prompt row
  in, the first generated token out; per-position K/V are scattered
  into the paged pool THROUGH the precomputed page coordinates
  (``serving/kvcache.py`` emits them; tail padding lands on the
  scratch page).
- ``prefill_chunk(caches, ids, start, valid, table, ...)`` — one
  kernel-sized SLICE of a prompt: ``valid`` tokens at positions
  ``start..start+valid-1`` (front-aligned in the padded ``ids`` row),
  K/V scattered into the sequence's pages, attention over the whole
  written history through the paged kernel (Sq = chunk length). The
  decode engine interleaves these at iteration boundaries so a long
  prompt never stalls the running batch for more than one chunk.
- ``decode_step(caches, ids, positions, tables)`` — one iteration of
  the continuous decode batch: (R,) current tokens in, (R,) next
  tokens out, each row reading its own history through its page-table
  row (``ops.pallas.flash_attention.paged_flash_attention`` on TPU /
  interpret, the dense reference off it) and writing its new K/V page
  slot in place.

All are ``jax.jit`` steps with ``donate_argnums=(0,)`` on the cache
pytree — the decode analog of the encoder path's per-shape CachedOp
executables (one compile per (rows, table-width) bucket, cached by
jax) — so the page pool updates IN PLACE: steady-state decode performs
no per-step cache-sized allocation (``MXNET_TPU_DECODE_DONATE=0``
disables donation for A/B; the resource-watermark test pins the
default).

Sampling is greedy argmax by DEFAULT — deterministic by construction,
what makes the solo-parity goldens byte-exact — with seeded
temperature/top-k/top-p layered on per request: the PRNG key is
``fold_in(PRNGKey(seed), position)``, a pure function of the request's
seed and the sampled position, NEVER of batch composition or iteration
timing — so a stream replayed on another seat after failover resamples
the identical tokens (the part-index dedupe / canary-golden contract).
"""
from __future__ import annotations

import warnings

import numpy as np

from .. import envvars

__all__ = ["PagedCausalLM"]

# XLA CPU cannot honor buffer donation (TPU/GPU can); jax warns once
# per compile — expected off-chip, pure noise in CPU test logs
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")


def _layer_norm(x, g, b, eps=1e-5):
    import jax.numpy as jnp

    m = jnp.mean(x, axis=-1, keepdims=True)
    v = jnp.mean(jnp.square(x - m), axis=-1, keepdims=True)
    return (x - m) / jnp.sqrt(v + eps) * g + b


def _sample_row(logits, temp, top_k, top_p, seed, pos):
    """Draw ONE token from one logits row. ``temp <= 0`` is greedy
    argmax — bitwise the pre-sampling behavior, kept as the default
    and the solo-parity lever. Otherwise: temperature-scale, keep the
    ``top_k`` highest logits (0 = all), keep the smallest
    highest-probability set whose mass reaches ``top_p``, draw from
    the rest. The PRNG key is ``fold_in(PRNGKey(seed), pos)`` — a pure
    function of the request's seed and the SEQUENCE position the
    logits came from, so the draw is independent of batch composition,
    chunking and which seat runs it: deterministic replay under
    failover and identical sequences for identical seeds."""
    import jax
    import jax.numpy as jnp

    vocab = logits.shape[-1]
    greedy = jnp.argmax(logits).astype(jnp.int32)
    lg = logits.astype(jnp.float32) \
        / jnp.maximum(temp.astype(jnp.float32), np.float32(1e-6))
    order = jnp.argsort(-lg)                    # token ids, best first
    ranks = jnp.argsort(order)                  # rank of each token
    kk = jnp.where(top_k > 0, top_k.astype(jnp.int32), np.int32(vocab))
    lg = jnp.where(ranks < kk, lg, np.float32(-1e30))
    probs = jax.nn.softmax(lg)
    sp = probs[order]                           # descending by rank
    cum = jnp.cumsum(sp)
    # a token survives top-p if the mass STRICTLY above it is < top_p
    # (the best token always survives, whatever its probability)
    keep = jnp.maximum(
        jnp.sum((cum - sp) < top_p.astype(jnp.float32)), 1)
    lg = jnp.where(ranks < keep, lg, np.float32(-1e30))
    key = jax.random.fold_in(jax.random.PRNGKey(seed), pos)
    sampled = jax.random.categorical(key, lg).astype(jnp.int32)
    return jnp.where(temp > np.float32(0.0), sampled, greedy)


class PagedCausalLM:
    """GPT-small-shaped causal LM with a paged decode path.

    Weights are freshly initialized (seeded ``Normal(0.02)``) — the
    serving plane under test is scheduling/transport/caching, not
    model quality; greedy argmax over deterministic weights gives
    byte-reproducible token sequences, which is exactly what the
    parity goldens need.

    Parameters mirror the bench legs: ``vocab``/``units``/``layers``/
    ``heads`` plus ``max_len`` (position-table size — the admission
    bound on prompt + generated length).
    """

    def __init__(self, vocab=256, units=64, layers=2, heads=4,
                 max_len=1024, seed=0, dtype="float32", donate=None,
                 interpret=None):
        import jax
        import jax.numpy as jnp

        if units % heads:
            raise ValueError(f"units {units} not divisible by heads "
                             f"{heads}")
        self.vocab = int(vocab)
        self.units = int(units)
        self.layers = int(layers)
        self.heads = int(heads)
        self.head_dim = self.units // self.heads
        self.max_len = int(max_len)
        self._interpret = interpret
        donate = (envvars.get("MXNET_TPU_DECODE_DONATE")
                  if donate is None else bool(donate))
        self.donate = donate
        rng = np.random.RandomState(seed)
        dt = jnp.dtype(dtype)

        def w(*shape):
            return jnp.asarray(rng.normal(0.0, 0.02, shape), dt)

        U, V, L = self.units, self.vocab, self.layers
        p = {"embed": w(V, U), "pos": w(self.max_len, U),
             "lnf_g": jnp.ones((U,), dt), "lnf_b": jnp.zeros((U,), dt),
             "head": w(U, V)}
        for i in range(L):
            p[f"l{i}_ln1_g"] = jnp.ones((U,), dt)
            p[f"l{i}_ln1_b"] = jnp.zeros((U,), dt)
            p[f"l{i}_ln2_g"] = jnp.ones((U,), dt)
            p[f"l{i}_ln2_b"] = jnp.zeros((U,), dt)
            for n in ("wq", "wk", "wv", "wo"):
                p[f"l{i}_{n}"] = w(U, U)
            p[f"l{i}_w1"] = w(U, 4 * U)
            p[f"l{i}_b1"] = jnp.zeros((4 * U,), dt)
            p[f"l{i}_w2"] = w(4 * U, U)
            p[f"l{i}_b2"] = jnp.zeros((U,), dt)
        self.params = p
        kw = {"donate_argnums": (0,)} if donate else {}
        self._prefill = jax.jit(self._prefill_impl, **kw)
        self._chunk = jax.jit(self._prefill_chunk_impl, **kw)
        self._decode = jax.jit(self._decode_impl, **kw)

    @property
    def spec(self):
        """The KV geometry the engine sizes its page pool from."""
        return {"n_layers": self.layers, "n_heads": self.heads,
                "head_dim": self.head_dim, "vocab": self.vocab,
                "max_len": self.max_len}

    # -- shared pieces ------------------------------------------------------
    def _qkv(self, h, i):
        """(..., U) -> three (..., H, D) projections."""
        p = self.params
        shape = h.shape[:-1] + (self.heads, self.head_dim)
        return ((h @ p[f"l{i}_wq"]).reshape(shape),
                (h @ p[f"l{i}_wk"]).reshape(shape),
                (h @ p[f"l{i}_wv"]).reshape(shape))

    def _mlp(self, x, i):
        import jax

        p = self.params
        return jax.nn.gelu(
            x @ p[f"l{i}_w1"] + p[f"l{i}_b1"]) @ p[f"l{i}_w2"] \
            + p[f"l{i}_b2"]

    def _ln(self, x, name):
        return _layer_norm(x, self.params[f"{name}_g"],
                           self.params[f"{name}_b"])

    def _write(self, caches, i, phys, off, k, v):
        """Scatter per-position K/V into layer ``i``'s page arrays.
        ``phys``/``off`` are (T,) page coordinates, ``k``/``v``
        (T, H, D)."""
        kc, vc = caches[2 * i], caches[2 * i + 1]
        kc = kc.at[phys, :, off, :].set(k)
        vc = vc.at[phys, :, off, :].set(v)
        return caches[:2 * i] + (kc, vc) + caches[2 * i + 2:]

    # -- prefill ------------------------------------------------------------
    def _prefill_impl(self, caches, ids, length, phys, off,
                      temp, top_k, top_p, seed):
        """One padded prompt row: ids (Lp,) int32, length scalar int32,
        phys/off (Lp,) page coordinates. Returns (first generated
        token (), updated caches). Dense causal self-attention (the
        whole prompt is visible at once — the encoder-shaped phase);
        K/V land in the pages for the decode steps to read back."""
        import jax.numpy as jnp

        p = self.params
        lp = ids.shape[0]
        positions = jnp.minimum(jnp.arange(lp, dtype=jnp.int32),
                                np.int32(self.max_len - 1))
        x = p["embed"][ids] + p["pos"][positions]
        col = jnp.arange(lp, dtype=jnp.int32)[None, :]
        row = jnp.arange(lp, dtype=jnp.int32)[:, None]
        causal = col <= row
        scale = np.float32(1.0 / np.sqrt(self.head_dim))
        for i in range(self.layers):
            h = self._ln(x, f"l{i}_ln1")
            q, k, v = self._qkv(h, i)          # (Lp, H, D)
            caches = self._write(caches, i, phys, off, k, v)
            s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32) * scale,
                           k.astype(jnp.float32))
            s = jnp.where(causal[None], s, np.float32(-1e30))
            s = s - jnp.max(s, axis=-1, keepdims=True)
            w_ = jnp.exp(s)
            w_ = w_ / jnp.sum(w_, axis=-1, keepdims=True)
            o = jnp.einsum("hqk,khd->qhd", w_, v.astype(jnp.float32))
            x = x + o.reshape(lp, self.units).astype(x.dtype) \
                @ p[f"l{i}_wo"]
            x = x + self._mlp(self._ln(x, f"l{i}_ln2"), i)
        h_last = x[length - 1]
        logits = self._ln(h_last, "lnf") @ p["head"]
        tok = _sample_row(logits, temp, top_k, top_p, seed, length - 1)
        return tok, caches

    # -- chunked prefill ----------------------------------------------------
    def _prefill_chunk_impl(self, caches, ids, start, valid, table,
                            temp, top_k, top_p, seed):
        """One prompt SLICE through the paged kernel: ids (C,) int32
        with the ``valid`` real tokens FRONT-aligned (positions
        ``start..start+valid-1``; the tail is padding), table (W,)
        int32 the sequence's padded page-table row. Each token's K/V
        is scattered into its page slot (padding to the scratch page),
        then the whole chunk attends over the written history with
        Sq = C and ``kv_len = start + C`` — row ``i`` of the chunk is
        position ``start + i``, so the kernel's causal mask
        ``col <= kv_len - Sq + row`` lands exactly on ``col <=
        start + i``: a chunk token sees every earlier position
        (including earlier tokens of its own chunk, already written
        this step) and nothing later. Padding rows attend into
        unwritten columns — garbage in, but row-wise ops keep it in
        the discarded rows. Returns (token sampled at position
        ``start + valid - 1``, caches) — only the chunk containing
        the prompt's last token turns that into the first generated
        token; earlier chunks' is dropped by the engine."""
        import jax.numpy as jnp

        from ..ops import pallas as _pallas
        from ..ops.pallas.flash_attention import (
            paged_attention_reference, paged_flash_attention)

        p = self.params
        c = ids.shape[0]
        width = table.shape[0]
        page_size = caches[0].shape[2]
        scratch = np.int32(caches[0].shape[0] - 1)
        idx = jnp.arange(c, dtype=jnp.int32)
        pos = start + idx
        live = idx < valid
        pos_c = jnp.minimum(pos, np.int32(self.max_len - 1))
        x = p["embed"][ids] + p["pos"][pos_c]       # (C, U)
        page_idx = jnp.minimum(pos // np.int32(page_size),
                               np.int32(width - 1))
        phys = jnp.where(live, table[page_idx], scratch)
        off = pos % np.int32(page_size)
        kvl = (start + np.int32(c))[None]           # (1,)
        attend = (paged_flash_attention if _pallas.pallas_enabled()
                  else paged_attention_reference)
        for i in range(self.layers):
            h = self._ln(x, f"l{i}_ln1")
            q, k, v = self._qkv(h, i)               # (C, H, D)
            caches = self._write(caches, i, phys, off, k, v)
            o = attend(jnp.transpose(q, (1, 0, 2))[None],   # (1,H,C,D)
                       caches[2 * i], caches[2 * i + 1],
                       table[None], kvl)
            o = jnp.transpose(o[0], (1, 0, 2)).reshape(c, self.units)
            x = x + o.astype(x.dtype) @ p[f"l{i}_wo"]
            x = x + self._mlp(self._ln(x, f"l{i}_ln2"), i)
        h_last = x[valid - 1]
        logits = self._ln(h_last, "lnf") @ p["head"]
        tok = _sample_row(logits, temp, top_k, top_p, seed,
                          start + valid - 1)
        return tok, caches

    # -- decode -------------------------------------------------------------
    def _decode_impl(self, caches, ids, positions, tables,
                     temps, top_ks, top_ps, seeds):
        """One continuous-batch iteration: ids/positions (R,) int32,
        tables (R, W) int32 page-table rows. Each row writes its new
        K/V at ``positions[r]`` and attends over its own pages up to
        ``positions[r] + 1`` — rows are numerically independent, which
        is what makes join/leave invisible to the sequences already
        running (the solo-parity contract)."""
        import jax.numpy as jnp

        from ..ops import pallas as _pallas
        from ..ops.pallas.flash_attention import (
            paged_attention_reference, paged_flash_attention)

        p = self.params
        r = ids.shape[0]
        pos_c = jnp.minimum(positions, np.int32(self.max_len - 1))
        x = p["embed"][ids] + p["pos"][pos_c]       # (R, U)
        page_size = caches[0].shape[2]
        phys = jnp.take_along_axis(
            tables, (positions // np.int32(page_size))[:, None],
            axis=1)[:, 0]
        off = positions % np.int32(page_size)
        kvl = positions + np.int32(1)
        attend = (paged_flash_attention if _pallas.pallas_enabled()
                  else paged_attention_reference)
        for i in range(self.layers):
            h = self._ln(x, f"l{i}_ln1")
            q, k, v = self._qkv(h, i)               # (R, H, D)
            caches = self._write(caches, i, phys, off, k, v)
            o = attend(q[:, :, None, :], caches[2 * i],
                       caches[2 * i + 1], tables, kvl)
            x = x + o[:, :, 0, :].reshape(r, self.units).astype(x.dtype) \
                @ p[f"l{i}_wo"]
            x = x + self._mlp(self._ln(x, f"l{i}_ln2"), i)
        logits = self._ln(x, "lnf") @ p["head"]
        import jax

        toks = jax.vmap(_sample_row)(logits, temps, top_ks, top_ps,
                                     seeds, positions)
        return toks.astype(jnp.int32), caches

    # -- public steps -------------------------------------------------------
    def prefill(self, caches, ids, length, phys, off,
                temperature=0.0, top_k=0, top_p=1.0, seed=0):
        import jax.numpy as jnp

        return self._prefill(caches, jnp.asarray(ids, jnp.int32),
                             jnp.asarray(length, jnp.int32),
                             jnp.asarray(phys, jnp.int32),
                             jnp.asarray(off, jnp.int32),
                             jnp.asarray(temperature, jnp.float32),
                             jnp.asarray(top_k, jnp.int32),
                             jnp.asarray(top_p, jnp.float32),
                             jnp.asarray(seed, jnp.int32))

    def prefill_chunk(self, caches, ids, start, valid, table,
                      temperature=0.0, top_k=0, top_p=1.0, seed=0):
        import jax.numpy as jnp

        return self._chunk(caches, jnp.asarray(ids, jnp.int32),
                           jnp.asarray(start, jnp.int32),
                           jnp.asarray(valid, jnp.int32),
                           jnp.asarray(table, jnp.int32),
                           jnp.asarray(temperature, jnp.float32),
                           jnp.asarray(top_k, jnp.int32),
                           jnp.asarray(top_p, jnp.float32),
                           jnp.asarray(seed, jnp.int32))

    def decode_step(self, caches, ids, positions, tables,
                    temperatures=None, top_ks=None, top_ps=None,
                    seeds=None):
        import jax.numpy as jnp

        ids = jnp.asarray(ids, jnp.int32)
        r = ids.shape[0]

        def _vec(v, fill, dt):
            if v is None:
                return jnp.full((r,), fill, dt)
            return jnp.asarray(v, dt)

        return self._decode(caches, ids,
                            jnp.asarray(positions, jnp.int32),
                            jnp.asarray(tables, jnp.int32),
                            _vec(temperatures, 0.0, jnp.float32),
                            _vec(top_ks, 0, jnp.int32),
                            _vec(top_ps, 1.0, jnp.float32),
                            _vec(seeds, 0, jnp.int32))
