"""Burn-rate + queue-depth autoscaling: the fleet buys its own seats.

The telemetry can judge the fleet (SLO burn rates, queue depth,
scoreboard) and the routing can shift load around a sick seat, but
capacity itself was still an operator decision. A
:class:`FleetAutoscaler` closes that loop over one or more fronting
routers (active/active peers share one autoscaler so their seat sets
stay identical) and an ``engine_factory``:

- **scale up** when the fleet's short-window burn rate OR the router
  queue depth holds above threshold for ``hold_s`` (a burst must not
  buy a seat), up to ``max_seats`` and rate-limited by ``cooldown_s``;
- **scale down** when an autoscaler-added seat has been idle (empty
  queue, burn under sustainable) for ``idle_s``, down to
  ``min_seats``;
- **replace** a seat the scoreboard holds unroutable for
  ``replace_s`` — the seat-kill drill's recovery path. Replacement is
  exempt from the cooldown: availability does not wait out a timer.

Every spawned seat admits traffic WARM: the factory's fresh engine is
started, replays the router's fleet-union warmup manifest against the
persistent compile cache (``warmup(manifest=...)``), and is
TTFT-probed with one direct request before ``add_engine`` exposes it
to traffic — the probe's wall time is the recorded
``ttft_ms`` (warm ≈ milliseconds; a cold spawn pays its compiles
here, never on a user request).

``MXNET_TPU_AUTOSCALE=0`` makes ``start()`` a no-op (no thread);
``evaluate_once`` stays drivable for scripted tests either way.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from .. import envvars
from ..telemetry import events as _events
from ..telemetry.registry import REGISTRY as _REGISTRY

__all__ = ["FleetAutoscaler"]


class FleetAutoscaler:
    """Spawn/retire engine seats behind router(s) from fleet signals.

    Parameters
    ----------
    routers : one ``ServingRouter`` or a list (active/active peers:
        every membership action is applied to ALL of them, which IS
        the seat-state sharing between peers fronting in-process
        engines).
    engine_factory : ``(engine_id) -> ServingEngine`` building a
        FRESH engine (never started); the autoscaler owns start /
        warmup / stop of the seats it creates.
    probe_tokens : tokens for the admit-warm TTFT probe (default a
        small arange request).
    Remaining knobs default from the ``MXNET_TPU_AUTOSCALE*``
    registry; ``clock`` is injectable for scripted tests.
    """

    def __init__(self, routers, engine_factory, min_seats=None,
                 max_seats=None, interval_s=None, burn_threshold=None,
                 queue_high=None, hold_s=None, cooldown_s=None,
                 idle_s=None, replace_s=None, probe_tokens=None,
                 clock=None, registry=None):
        reg = registry if registry is not None else _REGISTRY
        self.routers = list(routers) if isinstance(
            routers, (list, tuple)) else [routers]
        if not self.routers:
            raise ValueError("autoscaler needs at least one router")
        self._factory = engine_factory
        self.min_seats = int(min_seats if min_seats is not None
                             else envvars.get("MXNET_TPU_AUTOSCALE_MIN"))
        self.max_seats = int(max_seats if max_seats is not None
                             else envvars.get("MXNET_TPU_AUTOSCALE_MAX"))
        self.interval_s = float(
            interval_s if interval_s is not None
            else envvars.get("MXNET_TPU_AUTOSCALE_INTERVAL_S"))
        self.burn_threshold = float(
            burn_threshold if burn_threshold is not None
            else envvars.get("MXNET_TPU_AUTOSCALE_BURN"))
        self.queue_high = int(
            queue_high if queue_high is not None
            else envvars.get("MXNET_TPU_AUTOSCALE_QUEUE"))
        self.hold_s = float(hold_s if hold_s is not None
                            else envvars.get("MXNET_TPU_AUTOSCALE_HOLD_S"))
        self.cooldown_s = float(
            cooldown_s if cooldown_s is not None
            else envvars.get("MXNET_TPU_AUTOSCALE_COOLDOWN_S"))
        self.idle_s = float(idle_s if idle_s is not None
                            else envvars.get("MXNET_TPU_AUTOSCALE_IDLE_S"))
        self.replace_s = float(
            replace_s if replace_s is not None
            else envvars.get("MXNET_TPU_AUTOSCALE_REPLACE_S"))
        self._probe_tokens = (np.asarray(probe_tokens, np.int32)
                              if probe_tokens is not None
                              else np.arange(1, 9, dtype=np.int32))
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._spawned = {}          # engine_id -> engine (we own stop)
        self._auto_seats = []       # scale-up seat ids (LIFO retire)
        self._seat_seq = 0
        self._pressure_since = None
        self._idle_since = None
        self._down_since = {}       # engine_id -> first-seen-down t
        self._last_action_t = None
        self._census = {}           # model_id -> routable seats
        self.actions = []           # action records (drill surface)
        self._g_seats = reg.gauge(
            "mxnet_tpu_autoscaler_seats",
            "routable seats the autoscaler currently observes on its "
            "primary router")
        self._c_actions = reg.counter(
            "mxnet_tpu_autoscaler_actions_total",
            "autoscaler actions, by kind (scale_up / scale_down / "
            "replace)", ("action",))
        # per-model seat census as a labeled gauge: the named input
        # for per-model scaling, exported so it can be historied and
        # graphed — not just read off action records after the fact
        self._g_model_seats = reg.gauge(
            "mxnet_tpu_autoscaler_model_seats",
            "routable seats hosting each model on the primary router",
            ("model",))

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if not envvars.get("MXNET_TPU_AUTOSCALE"):
            return self
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True,
                name="mxnet_tpu_autoscaler")
            self._thread.start()
        _events.emit("autoscale_start", min=self.min_seats,
                     max=self.max_seats, burn=self.burn_threshold,
                     queue=self.queue_high)
        return self

    def stop(self, stop_seats=False):
        with self._lock:
            self._stop.set()
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10.0)
        if stop_seats:
            with self._lock:
                spawned = list(self._spawned.values())
                self._spawned.clear()
            for eng in spawned:
                try:
                    eng.stop(drain=False, timeout=10.0)
                except Exception:
                    pass

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_once()
            except Exception as e:
                # one broken evaluation must not kill autoscaling
                _events.emit("autoscale_error", error=repr(e))

    # -- signals ------------------------------------------------------------
    def _primary(self):
        """The first RUNNING router (falls back to the first): a dead
        active/active primary must not freeze the autoscaler on its
        last scoreboard — the survivor's live signals take over."""
        for router in self.routers:
            try:
                if router.running:
                    return router
            except Exception:
                continue
        return self.routers[0]

    def _signals(self):
        """(burn, queue_depth, board) off the primary router: the max
        short-window burn across its ratio objectives, the router
        admission-queue depth, and the scoreboard."""
        from ..telemetry.slo import max_short_burn

        router = self._primary()
        try:
            slo = router.slo_snapshot()
        except Exception:
            slo = None
        snap = router.snapshot()
        return (max_short_burn(slo), snap.get("queue_depth") or 0,
                snap["engines"])

    @staticmethod
    def _model_seats(board):
        """model_id -> count of ROUTABLE seats hosting it, off the
        scoreboard's per-seat ``models`` maps. Attached to every
        action record so a drill can see WHICH model's capacity an
        action changed (a fleet serving two models at 3:1 seat split
        scales them 3:1, not blindly)."""
        census = {}
        for row in board.values():
            if not row.get("routable"):
                continue
            models = row.get("models")
            if isinstance(models, dict):
                for mid in models:
                    census[mid] = census.get(mid, 0) + 1
        return census

    @staticmethod
    def _engine_models(engine):
        """Model ids one engine hosts (best effort, for records)."""
        try:
            models = engine.snapshot().get("models")
            return sorted(models) if isinstance(models, dict) else None
        except Exception:
            return None

    # -- one tick -----------------------------------------------------------
    def evaluate_once(self, now=None):
        """One evaluation: replacement first (availability), then the
        held scale-up/scale-down decisions. Returns the action taken
        (an action record dict) or None."""
        now = self._clock() if now is None else now
        burn, queue_depth, board = self._signals()
        routable = [eid for eid, row in board.items()
                    if row.get("routable")]
        self._g_seats.set(len(routable))
        census = self._model_seats(board)
        for model_id, seats in census.items():
            self._g_model_seats.labels(model=str(model_id)).set(seats)
        # a model whose last seat left must read 0, not its stale count
        for model_id in self._census:
            if model_id not in census:
                self._g_model_seats.labels(model=str(model_id)).set(0)
        self._census = census

        # -- replace dead seats (cooldown-exempt) ---------------------------
        for eid, row in board.items():
            if row.get("routable"):
                self._down_since.pop(eid, None)
                continue
            first = self._down_since.setdefault(eid, now)
            if now - first >= self.replace_s:
                self._down_since.pop(eid, None)
                return self._replace(eid, now)

        # -- scale up -------------------------------------------------------
        pressured = ((burn is not None and burn > self.burn_threshold)
                     or queue_depth >= self.queue_high)
        if pressured:
            self._idle_since = None
            if self._pressure_since is None:
                self._pressure_since = now
            held = now - self._pressure_since >= self.hold_s
            if held and len(board) < self.max_seats \
                    and self._cooled(now):
                self._pressure_since = None
                return self._scale_up(now, burn, queue_depth)
            return None
        self._pressure_since = None

        # -- scale down -----------------------------------------------------
        idle = (queue_depth == 0
                and (burn is None or burn <= 1.0))
        if not idle:
            self._idle_since = None
            return None
        if self._idle_since is None:
            self._idle_since = now
        if (now - self._idle_since >= self.idle_s
                and self._auto_seats
                and len(routable) > self.min_seats
                and self._cooled(now)):
            self._idle_since = None
            return self._scale_down(now)
        return None

    def _cooled(self, now):
        return (self._last_action_t is None
                or now - self._last_action_t >= self.cooldown_s)

    # -- actions ------------------------------------------------------------
    def _spawn_warm(self, engine_id):
        """Build, start, manifest-warm and TTFT-probe one fresh seat
        — everything BEFORE it can see a user request. Returns
        (engine, ttft_ms, manifest_shapes). A failure anywhere stops
        the half-built engine before re-raising — a failed spawn must
        not leak a worker thread (and the caller retries on a later
        tick)."""
        engine = self._factory(engine_id)
        try:
            engine.start()
            try:
                manifest = self._primary().warmup_manifest()
            except Exception:
                manifest = None
            shapes = 0
            if manifest and manifest.get("shapes"):
                shapes = len(manifest["shapes"])
                engine.warmup(manifest=manifest)
            t0 = time.perf_counter()
            engine.submit(self._probe_tokens).result(timeout=600.0)
            ttft_ms = round((time.perf_counter() - t0) * 1e3, 3)
        except BaseException:
            try:
                engine.stop(drain=False, timeout=10.0)
            except Exception:
                pass
            raise
        return engine, ttft_ms, shapes

    def _record(self, action, engine_id, now, **extra):
        self._last_action_t = now
        rec = dict(action=action, engine_id=engine_id,
                   model_seats=dict(self._census), **extra)
        self.actions.append(rec)
        self._c_actions.labels(action=action).inc()
        _events.emit("autoscale_action", **rec)
        return rec

    def _add_everywhere(self, engine_id, engine):
        for router in self.routers:
            router.add_engine(engine_id, engine)

    def _remove_everywhere(self, engine_id):
        for router in self.routers:
            try:
                router.remove_engine(engine_id)
            except KeyError:
                pass

    def _scale_up(self, now, burn, queue_depth):
        self._seat_seq += 1
        engine_id = f"auto{self._seat_seq}"
        engine, ttft_ms, shapes = self._spawn_warm(engine_id)
        with self._lock:
            self._spawned[engine_id] = engine
            self._auto_seats.append(engine_id)
        self._add_everywhere(engine_id, engine)
        return self._record("scale_up", engine_id, now,
                            ttft_ms=ttft_ms, manifest_shapes=shapes,
                            models=self._engine_models(engine),
                            burn=(round(burn, 3)
                                  if burn is not None else None),
                            queue_depth=queue_depth)

    def _scale_down(self, now):
        with self._lock:
            engine_id = self._auto_seats.pop()
            engine = self._spawned.pop(engine_id, None)
        self._remove_everywhere(engine_id)
        if engine is not None:
            # drain=True: the seat finishes what it already accepted
            try:
                engine.stop(drain=True, timeout=60.0)
            except Exception as e:
                _events.emit("autoscale_error", engine_id=engine_id,
                             error=repr(e))
        return self._record("scale_down", engine_id, now)

    def _replace(self, engine_id, now):
        """A seat held unroutable past the debounce: admit a
        manifest-warmed replacement under the SAME id (dashboards and
        drills keep one name per chip). Spawn-THEN-remove: a failed
        spawn leaves the dead seat on the boards, so the unroutable
        debounce re-arms and replacement is retried on a later tick —
        never a seat silently gone from the fleet."""
        engine, ttft_ms, shapes = self._spawn_warm(engine_id)
        # the old incarnation must STOP even when the caller built it
        # (a wedged-but-alive engine left running would keep writing
        # metric families under the id its replacement now owns) —
        # grab the handle BEFORE removal drops the seat
        dead = self._primary().engine_handle(engine_id)
        self._remove_everywhere(engine_id)
        with self._lock:
            dead = self._spawned.pop(engine_id, None) or dead
            self._spawned[engine_id] = engine
        if dead is not None:
            try:
                dead.stop(drain=False, timeout=10.0)
            except Exception:
                pass
        self._add_everywhere(engine_id, engine)
        return self._record("replace", engine_id, now,
                            ttft_ms=ttft_ms, manifest_shapes=shapes,
                            models=self._engine_models(engine))
